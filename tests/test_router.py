"""Fleet router + serving job type (docs/serving.md "Fleet serving").

The contract under test, bottom-up: the FleetRouter's routing policies
against scriptable stub replicas (least-loaded pick, prefix-affinity
stickiness + saturation spill, 429 retry honoring Retry-After, ejection
on failed /healthz and readmission), the driver's publish_ports /
roll_task RPCs against a scripted provisioner, and — the acceptance
e2e — a real driver gang-launching two TINY SlotServer replica
processes, the router completing a burst byte-identical to a solo
in-process server with one replica hard-killed mid-burst (restart under
budget + router retry = latency, never a failed request).
"""

import json
import re
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

import tony_tpu.constants as c
from tony_tpu.metrics import (
    ROUTER_AFFINITY_HIT_RATIO,
    ROUTER_REPLICA_UP,
    ROUTER_REPLICAS_LIVE,
    ROUTER_ROUTING_SECONDS,
)
from tony_tpu.router import (
    DriverDiscovery,
    FleetRouter,
    FleetSaturatedError,
    NoReplicaError,
    make_handler,
)

# same golden exposition-line regex as the other metrics suites
_PROM_LINE = re.compile(
    r"^(# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]* .+|"
    r"[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? [^\s]+)$")


class StubReplica:
    """A scriptable fake serve endpoint: /generate, /healthz, /stats.
    Behavior is mutated by tests between calls (the handler reads the
    attributes live)."""

    def __init__(self, name: str):
        self.name = name
        self.healthy = True
        self.queued = 0
        self.active = 0
        self.slots = 2
        self.max_queue = 0
        self.retry_after = 2
        self.shed_next = 0          # serve this many 429s first
        self.fail_next = 0          # ... or this many 500s
        self.client_error_next = 0  # ... or this many 400s
        # advertised model registry (the serve /stats "models" keys);
        # None = legacy replica without the field
        self.models: list | None = None
        # disaggregated serving: role advertised on /stats (None =
        # legacy roleless replica); a prefill-role stub answers
        # /generate with finish_reason="prefilled" + this handoff
        # payload (None = export stash aged out); /kv/import POSTs
        # land in import_payloads and answer like a decode completion
        self.role: str | None = None
        self.handoff: dict | None = None
        self.import_payloads: list[dict] = []
        self.delay_s = 0.0
        # mid-request death: sleep, then sever the connection with no
        # response (what a SIGKILL looks like to the router's POST)
        self.abort_after_s = 0.0
        # streaming: when a payload asks stream=true and stream_total
        # is set, answer with SSE token-delta frames. The LOGICAL
        # stream is a deterministic function of the prompt (both
        # replicas of a failover pair agree), emitted from position 0
        # INCLUDING any resume prefix — the serve contract. Severing
        # after stream_die_after_chunks frames emulates a mid-stream
        # SIGKILL.
        self.stream_total: int | None = None
        self.stream_chunk = 2
        self.stream_die_after_chunks: int | None = None
        self.received: list[list] = []
        self.payloads: list[dict] = []      # full /generate payloads
        # /progress: emitted-so-far tokens served for ANY polled key
        # (None = pretend no live request, the endpoint returns {})
        self.progress_tokens: list[int] | None = None
        self._lock = threading.Lock()
        stub = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, obj, headers=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._send(200 if stub.healthy else 503,
                               {"healthy": stub.healthy})
                elif self.path == "/stats":
                    payload = {
                        "queued": stub.queued, "active": stub.active,
                        "slots": stub.slots, "max_queue": stub.max_queue,
                        "retry_after_s": stub.retry_after}
                    if stub.models is not None:
                        payload["models"] = {m: {} for m in stub.models}
                    if stub.role is not None:
                        payload["role"] = stub.role
                    self._send(200, payload)
                elif self.path.partition("?")[0] == "/progress":
                    # serve-contract shape: {key: {tokens, prompt_tokens}}
                    from urllib.parse import parse_qs, urlparse

                    qs = parse_qs(urlparse(self.path).query)
                    keys = [k for ks in qs.get("keys", [])
                            for k in ks.split(",") if k]
                    with stub._lock:
                        toks = stub.progress_tokens
                    self._send(200, {} if toks is None else {
                        k: {"tokens": list(toks), "prompt_tokens": 1}
                        for k in keys})
                else:
                    self._send(404, {})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", "0"))
                payload = json.loads(self.rfile.read(n) or b"{}")
                path = self.path.partition("?")[0]
                with stub._lock:
                    if stub.shed_next > 0:
                        stub.shed_next -= 1
                        self._send(429, {"error": "queue full"}, headers={
                            "Retry-After": str(stub.retry_after)})
                        return
                    if stub.fail_next > 0:
                        stub.fail_next -= 1
                        self._send(500, {"error": "boom"})
                        return
                    if stub.client_error_next > 0:
                        stub.client_error_next -= 1
                        self._send(400, {"error": "unknown model"})
                        return
                    if path == "/kv/import":
                        stub.import_payloads.append(dict(payload))
                    else:
                        stub.received.append(list(payload["prompt"]))
                        stub.payloads.append(dict(payload))
                if stub.abort_after_s:
                    time.sleep(stub.abort_after_s)
                    self.connection.close()     # died mid-request
                    return
                if stub.delay_s:
                    time.sleep(stub.delay_s)
                if path == "/kv/import":
                    # decode leg: resume from the imported blocks — a
                    # deterministic function of the entry's prompt
                    base = sum(payload.get("entry", {})
                               .get("prompt", [0])) % 100
                    self._send(200, {
                        "id": len(stub.import_payloads),
                        "tokens": [base + 1, base + 2],
                        "finish_reason": "length"})
                    return
                if stub.role == "prefill":
                    # prefill specialist: zero tokens + handoff payload
                    resp = {"id": len(stub.received), "tokens": [],
                            "finish_reason": "prefilled"}
                    if stub.handoff is not None:
                        resp["handoff"] = stub.handoff
                    self._send(200, resp)
                    return
                if payload.get("stream") and stub.stream_total:
                    # SSE contract: the full logical stream from
                    # position 0 (resume prefix is a true prefix of it
                    # by construction), chunked; optionally die mid-way
                    base = sum(payload["prompt"]) % 100
                    logical = [base + i
                               for i in range(stub.stream_total)]
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/event-stream")
                    self.end_headers()
                    sent_chunks = 0
                    for i in range(0, len(logical), stub.stream_chunk):
                        if (stub.stream_die_after_chunks is not None
                                and sent_chunks >=
                                stub.stream_die_after_chunks):
                            self.connection.close()     # SIGKILL look
                            return
                        frame = json.dumps(
                            {"tokens":
                             logical[i:i + stub.stream_chunk]})
                        self.wfile.write(
                            b"data: " + frame.encode() + b"\n\n")
                        self.wfile.flush()
                        sent_chunks += 1
                        time.sleep(0.01)
                    final = json.dumps(
                        {"id": len(stub.received),
                         "finish_reason": "length",
                         "n_tokens": len(logical)})
                    self.wfile.write(
                        b"data: " + final.encode() + b"\n\n")
                    self.wfile.flush()
                    return
                # serve-contract resume semantics: the response tokens
                # INCLUDE the teacher-forced prefix
                self._send(200, {
                    "id": len(stub.received),
                    "tokens": list(payload.get("resume_tokens", []))
                    + [len(payload["prompt"])],
                    "finish_reason": "length"})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    @property
    def endpoint(self):
        return (self.name, "127.0.0.1", self.port)

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def stubs():
    created = []

    def make(*names):
        for name in names:
            created.append(StubReplica(name))
        return created if len(created) > 1 else created[0]

    yield make
    for s in created:
        s.close()


def _router(reps, **kw):
    kw.setdefault("seed", 0)
    # unit tests drive health_tick() by hand and expect every tick to
    # pull /stats (production throttles to every 4th — see stats_every)
    kw.setdefault("stats_every", 1)
    return FleetRouter([s.endpoint for s in reps], **kw)


# --------------------------------------------------------------------------
# routing policies against stubs
# --------------------------------------------------------------------------

def test_least_loaded_pick(stubs):
    """Un-keyed requests (no full prefix block) go to the replica with
    the smallest queued+active load from its /stats."""
    a, b = stubs("a", "b")
    a.queued, b.queued = 5, 0
    router = _router([a, b], prefill_chunk=8)
    router.health_tick()            # pull /stats
    for _ in range(3):
        router.generate([1, 2, 3], max_new_tokens=1, timeout_s=5)
    assert len(b.received) == 3 and not a.received
    a.queued, b.queued = 0, 5
    router.health_tick()
    router.generate([4, 5], max_new_tokens=1, timeout_s=5)
    assert len(a.received) == 1
    st = router.stats()
    assert st["requests"] == 4 and st["failed"] == 0
    assert st["affinity"]["requests"] == 0      # nothing keyed


def test_model_aware_routing(stubs):
    """Requests naming a model route (and spill) ONLY among replicas
    advertising it on /stats; a replica without the field (legacy)
    serves anything; a model nobody advertises fails with a clear
    NoReplicaError after the deadline."""
    from tony_tpu.router import RouterClientError

    a, b, legacy = stubs("a", "b", "legacy")
    a.models, b.models = ["alpha"], ["beta", "alpha"]
    a.queued, b.queued, legacy.queued = 0, 0, 0
    router = _router([a, b, legacy], prefill_chunk=8)
    router.health_tick()
    # beta lives only on b — every beta request lands there, regardless
    # of load ordering
    a.queued = 0
    for _ in range(3):
        resp = router.generate([1, 2, 3], max_new_tokens=1, timeout_s=5,
                               model="beta")
        assert resp["replica"] == "b"
    assert all(p.get("model") == "beta" for p in b.payloads)
    # alpha lives on a and b: least-loaded picks among exactly those +
    # the legacy (advertises nothing = serves anything)
    got = {router.generate([9, 9], max_new_tokens=1, timeout_s=5,
                           model="alpha")["replica"] for _ in range(6)}
    assert got <= {"a", "b", "legacy"}
    # spill respects the model dimension: alpha's pick saturated ->
    # next ALPHA-capable candidate, never a beta-only replica
    # (construct: advertise alpha only on a, saturate a)
    b.models = ["beta"]
    router.health_tick()
    a.shed_next = 1
    resp = router.generate([2, 4, 6], max_new_tokens=1, timeout_s=5,
                           model="alpha")
    assert resp["replica"] == "legacy", resp
    # a model nobody advertises: fast, clear failure
    legacy.models = ["alpha", "beta"]
    router.health_tick()
    with pytest.raises(NoReplicaError, match="ghost"):
        router.generate([1], max_new_tokens=1, timeout_s=0.6,
                        model="ghost")
    # replica 400s (stale advertisement): no retry, no ejection,
    # surfaced as a client error
    b.client_error_next = 1
    with pytest.raises(RouterClientError, match="unknown model"):
        router.generate([5, 5], max_new_tokens=1, timeout_s=5,
                        model="beta")
    assert router.replicas["b"].up, "a 4xx must not eject the replica"
    assert router.stats()["replicas"]["b"]["models"] == ["beta"]


def test_affinity_stickiness_and_spill(stubs):
    """Requests sharing chunk-aligned prompt blocks stick to ONE replica
    (whatever their suffixes); when the sticky replica sheds, the
    request spills to the rendezvous second choice and counts a retry."""
    a, b = stubs("a", "b")
    router = _router([a, b], prefill_chunk=4)
    template = [7, 1, 7, 2]                     # one full chunk
    for suffix in ([9], [10], [11, 12], []):
        router.generate(template + suffix, max_new_tokens=1, timeout_s=5)
    sticky, other = (a, b) if a.received else (b, a)
    assert len(sticky.received) == 4 and not other.received
    assert router.stats()["affinity"]["hit_ratio"] == 1.0

    # a different template may land elsewhere, but is itself sticky
    other_template = [5, 5, 5, 5, 5, 5, 5, 5]
    first = router.generate(other_template, max_new_tokens=1,
                            timeout_s=5)["replica"]
    again = router.generate(other_template + [1], max_new_tokens=1,
                            timeout_s=5)["replica"]
    assert first == again

    # saturation spill: the sticky replica sheds once -> the SAME
    # request completes on the other replica, immediately (no sleep:
    # only the sticky replica is backpressuring)
    sticky.shed_next = 1
    t0 = time.monotonic()
    resp = router.generate(template + [42], max_new_tokens=1, timeout_s=5)
    assert time.monotonic() - t0 < 1.0
    assert resp["replica"] == other.name and resp["retries"] == 1
    assert other.received[-1] == template + [42]
    st = router.stats()["replicas"]
    assert st[sticky.name]["shed"] == 1
    assert st[other.name]["retries"] == 1
    # the spilled request dents the affinity hit ratio
    assert router.stats()["affinity"]["hit_ratio"] < 1.0


def test_429_retry_honors_retry_after(stubs):
    """When EVERY live replica sheds, the router sleeps a jittered
    fraction of the smallest Retry-After before re-asking — and gives up
    with FleetSaturatedError when the deadline lands first."""
    a, b = stubs("a", "b")
    a.shed_next = b.shed_next = 1
    a.retry_after = b.retry_after = 1
    router = _router([a, b], prefill_chunk=4)
    t0 = time.monotonic()
    resp = router.generate([1, 2, 3, 4], max_new_tokens=1, timeout_s=10)
    wall = time.monotonic() - t0
    # both replicas shed once, then the jittered wait (>= 0.5 * 1s), then
    # success on a re-pick
    assert resp["retries"] == 2
    assert wall >= 0.5, f"router must honor Retry-After, waited {wall:.2f}s"

    # saturated past the deadline -> an honest shed with the advertised
    # Retry-After, not a timeout
    a.shed_next = b.shed_next = 10 ** 6
    a.retry_after = b.retry_after = 7
    with pytest.raises(FleetSaturatedError) as e:
        router.generate([1, 2, 3, 4], max_new_tokens=1, timeout_s=0.5)
    assert e.value.retry_after_s == 7
    assert router.stats()["shed"] == 1


def test_transport_error_ejects_and_retries(stubs):
    """A dead endpoint (nothing listening) is ejected on first contact
    and the request completes elsewhere — zero caller-visible failures."""
    b = stubs("b")
    dead = ("a", "127.0.0.1", 1)        # port 1: connection refused
    router = FleetRouter([dead, b.endpoint], prefill_chunk=4, seed=0)
    # un-keyed prompt -> least-loaded order, name tie-break: "a" first
    resp = router.generate([1, 2, 3], max_new_tokens=1, timeout_s=10)
    assert resp["replica"] == "b"
    st = router.stats()
    assert st["replicas"]["a"]["up"] is False
    assert st["replicas"]["a"]["ejections"] == 1
    assert st["failed"] == 0
    # connection REFUSED = the request never reached the replica: an
    # ordinary re-route, NOT a mid-request failover — the failover
    # counter stays an honest mid-stream-recovery signal
    assert st["failovers"] == 0 and st["resumed_tokens"] == 0


def test_failover_resumes_with_emitted_prefix(stubs):
    """Replay-aware failover (docs/serving.md "Request durability &
    replay"): a replica that 5xxes mid-request is re-asked for its
    /progress, and the resubmission to the rendezvous runner-up carries
    the emitted prefix as resume_tokens — the caller's tokens include
    the prefix (no restart from scratch), router_failovers_total and
    the trace record it."""
    a, b = stubs("a", "b")
    router = _router([a, b], prefill_chunk=4)
    template = [7, 1, 7, 2]                     # keyed -> sticky replica
    router.generate(template + [1], max_new_tokens=1, timeout_s=5)
    sticky, other = (a, b) if a.received else (b, a)
    # every routed request carries a progress handle for the polls
    assert "progress_key" in sticky.payloads[-1]
    sticky.fail_next = 1
    sticky.progress_tokens = [41, 42, 43]       # what it emitted pre-death
    resp = router.generate(template + [2], max_new_tokens=8, timeout_s=10)
    assert resp["replica"] == other.name and resp["retries"] == 1
    # the resubmission carried the prefix; the response includes it
    assert other.payloads[-1]["resume_tokens"] == [41, 42, 43]
    assert resp["tokens"][:3] == [41, 42, 43]
    st = router.stats()
    assert st["failovers"] == 1 and st["resumed_tokens"] == 3
    assert st["failed"] == 0
    assert "router_failovers_total 1" in router.prometheus_metrics()
    # health-tick progress polling journals prefixes for OUTSTANDING
    # requests only; a terminal request's key is dropped
    assert not router._outstanding and not router._resume


def test_failover_health_poll_prefix_survives_dead_replica(stubs):
    """A SIGKILLed replica can't answer the failover-time /progress
    re-ask — the prefix journaled by the health loop's LAST poll is
    what the resubmission carries. Staged: the health tick polls the
    in-flight request's progress, then the replica drops dead (connection
    refused), and the retry still resumes from the polled prefix."""
    a, b = stubs("a", "b")
    router = _router([a, b], prefill_chunk=4)
    template = [7, 1, 7, 2]
    router.generate(template, max_new_tokens=1, timeout_s=5)
    sticky, other = (a, b) if a.received else (b, a)
    sticky.progress_tokens = [9, 8]
    # the in-flight request dies mid-decode: the POST's connection is
    # severed with no response after a beat (a SIGKILL, as the router
    # sees it) — but first the health loop gets a poll in
    sticky.abort_after_s = 1.5
    res = {}

    def call():
        try:
            res["r"] = router.generate(template + [5], max_new_tokens=8,
                                       timeout_s=20)
        except Exception as e:          # pragma: no cover
            res["r"] = e

    t = threading.Thread(target=call)
    t.start()
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and not router._outstanding:
        time.sleep(0.01)
    router.health_tick()                # journals the polled prefix
    with router._lock:
        polled = dict(router._resume)
    assert list(polled.values()) == [[9, 8]], polled
    # the dead replica answers nothing at failover time: the re-ask
    # yields no info and the journaled prefix stands
    sticky.progress_tokens = None
    t.join(timeout=30)
    assert not t.is_alive()
    resp = res["r"]
    assert isinstance(resp, dict), resp
    assert resp["replica"] == other.name
    assert other.payloads[-1]["resume_tokens"] == [9, 8]
    assert resp["tokens"][:2] == [9, 8]
    assert router.stats()["failovers"] >= 1


def test_affinity_key_is_per_model_and_template(stubs):
    """ISSUE 14 satellite (PR 13 leftover): the rendezvous key is
    ``(model, template)``, not template alone — two registered models
    sharing a prompt template land on their OWN sticky replicas (each
    engine owns its own prefix pool; colliding them would double one
    replica's trie pressure while its peers idle), and each pair stays
    sticky."""
    reps = stubs("a", "b", "c")
    for s in reps:
        s.models = ["alpha", "beta"]
    router = _router(list(reps), prefill_chunk=4)
    router.health_tick()
    template = [7, 1, 7, 2]                     # one full chunk, shared
    # the digests themselves must differ (and differ from model-less)
    keys = {router.route_key(template, m) for m in
            ("alpha", "beta", None)}
    assert len(keys) == 3, "model must namespace the affinity key"
    # both (model, template) pairs are sticky across suffixes...
    by_model = {}
    for model in ("alpha", "beta"):
        got = {router.generate(template + sfx, max_new_tokens=1,
                               timeout_s=5, model=model)["replica"]
               for sfx in ([], [9], [10, 11])}
        assert len(got) == 1, f"{model} requests must stay sticky"
        by_model[model] = got.pop()
    # ...and the three stubs give the pair every chance to separate;
    # with 3 replicas two independent rendezvous draws collide 1/3 of
    # the time, so assert on the KEYS (deterministic), and record the
    # placement for the curious
    ranked_a = router._ranked_locked(router.route_key(template, "alpha"))
    ranked_b = router._ranked_locked(router.route_key(template, "beta"))
    assert [r.name for r in ranked_a] != [r.name for r in ranked_b], (
        "two models sharing a template must not share a rendezvous "
        "ranking")
    assert router.stats()["affinity"]["hit_ratio"] == 1.0


def test_stream_relay_and_midstream_failover(stubs):
    """Streaming pass-through (the PR 7 follow-up resolved): the router
    relays a replica's SSE stream token-by-token; when the replica dies
    MID-STREAM, the resume prefix is harvested from the stream itself
    (no /progress poll needed), the rendezvous runner-up resumes, the
    prefix re-send is deduped, and the client's concatenated stream is
    exactly the logical stream — delivered once, in order."""
    a, b = stubs("a", "b")
    for s in (a, b):
        s.stream_total = 6
        s.stream_chunk = 2
    router = _router([a, b], prefill_chunk=4)
    template = [7, 1, 7, 2]
    base = sum(template) % 100
    logical = [base + i for i in range(6)]
    # clean relay first: every chunk forwarded, counters move
    got: list[list[int]] = []
    resp = router.generate(template, max_new_tokens=6, timeout_s=10,
                           on_tokens=lambda t: got.append(list(t)))
    sticky, other = (a, b) if a.received else (b, a)
    assert [t for c in got for t in c] == logical == resp["tokens"]
    assert len(got) >= 3, "relay must be incremental"
    assert resp["finish_reason"] == "length"
    st = router.stats()
    assert st["streamed_tokens"] == 6 and st["streams_active"] == 0
    assert st["stream_failovers"] == 0
    # now the sticky replica dies after ONE chunk (2 tokens)
    sticky.stream_die_after_chunks = 1
    got2: list[list[int]] = []
    resp2 = router.generate(template + [3], max_new_tokens=6,
                            timeout_s=20,
                            on_tokens=lambda t: got2.append(list(t)))
    flat = [t for c in got2 for t in c]
    logical2 = [(sum(template) + 3) % 100 + i for i in range(6)]
    assert flat == logical2 == resp2["tokens"], (
        "failover must dedupe the re-sent prefix: client sees the "
        "logical stream exactly once")
    assert resp2["replica"] == other.name
    # the resubmission carried the harvested 2-token prefix
    assert other.payloads[-1]["resume_tokens"] == logical2[:2]
    assert other.payloads[-1]["stream"] is True
    st = router.stats()
    assert st["stream_failovers"] == 1 and st["failovers"] == 1
    assert st["failed"] == 0
    assert st["resumed_tokens"] == 2
    metrics = router.prometheus_metrics()
    assert "router_stream_failovers_total 1" in metrics
    assert "router_streams_active 0" in metrics
    # consumer death: the client callback raising surfaces as
    # StreamConsumerError — no retry, and no NEW ejection (the sticky
    # replica's earlier mid-stream death was correctly ejected; the
    # health tick readmits it first)
    from tony_tpu.router import StreamConsumerError

    sticky.stream_die_after_chunks = None
    router.health_tick()                # readmit the revived sticky
    assert all(r.up for r in router.replicas.values())
    ejections_before = sum(r.ejections
                           for r in router.replicas.values())

    def boom(_):
        raise BrokenPipeError("client gone")

    with pytest.raises(StreamConsumerError):
        router.generate(template + [4], max_new_tokens=6, timeout_s=10,
                        on_tokens=boom)
    assert router.stats()["stream_disconnects"] == 1
    assert sum(r.ejections for r in router.replicas.values()) == \
        ejections_before, "a vanished CLIENT must not eject a replica"


def test_router_own_healthz_distinct_from_replicas(stubs):
    """The router-level /healthz (the ROADMAP router-HA slice): 200
    while the router can route — replicas in rotation AND the
    maintenance loop (once started) alive — 503 when the fleet is gone
    or the router is wedged/stopped, so an upstream LB ejects a dead
    ROUTER exactly like a dead replica."""
    a = stubs("a")
    router = _router([a], prefill_chunk=4, eject_after=1)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(router))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()

    def healthz():
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
                return r.status, json.loads(r.read().decode())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read().decode())

    try:
        # statically configured, loop not started: routable, and the
        # payload says the maintenance loop isn't running
        status, payload = healthz()
        assert status == 200 and payload["healthy"] is True
        assert payload["health_loop_alive"] is None
        router.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            status, payload = healthz()
            if payload["health_loop_alive"] is True:
                break
            time.sleep(0.01)
        assert status == 200 and payload["health_loop_alive"] is True
        # fleet gone -> 503 (the router cannot complete a request)
        a.healthy = False
        router.health_tick()
        status, payload = healthz()
        assert status == 503 and payload["live"] == 0
        a.healthy = True
        router.health_tick()
        assert healthz()[0] == 200
        # a stopped/wedged router is out of rotation even with a live
        # fleet behind it
        router.shutdown()
        status, payload = healthz()
        assert status == 503 and payload["health_loop_alive"] is False
        assert payload["live"] == 1, "replicas are fine; the ROUTER died"
    finally:
        httpd.shutdown()
        httpd.server_close()
        router.shutdown()


def test_ejection_on_healthz_and_readmission(stubs):
    """The health loop ejects a replica after eject_after consecutive
    failed /healthz probes and readmits it on the first success."""
    a, b = stubs("a", "b")
    router = _router([a, b], prefill_chunk=4, eject_after=2)
    a.healthy = False
    router.health_tick()
    assert router.stats()["replicas"]["a"]["up"] is True    # one strike
    router.health_tick()
    st = router.stats()
    assert st["replicas"]["a"]["up"] is False and st["live"] == 1
    # keyed traffic for the ejected replica's templates flows to b
    for suffix in range(4):
        router.generate([3, 1, 4, 1, suffix], max_new_tokens=1, timeout_s=5)
    assert len(b.received) == 4 and not a.received
    a.healthy = True
    router.health_tick()
    assert router.stats()["replicas"]["a"]["up"] is True
    assert router.stats()["live"] == 2


def test_no_live_replica_raises(stubs):
    a = stubs("a")
    a.healthy = False
    router = _router([a], prefill_chunk=4, eject_after=1)
    router.health_tick()
    with pytest.raises(NoReplicaError):
        router.generate([1, 2, 3, 4], max_new_tokens=1, timeout_s=0.6)


def test_router_metrics_exposition(stubs):
    """GET /metrics parses as Prometheus text and carries the router_*
    families with per-replica labels."""
    a, b = stubs("a", "b")
    router = _router([a, b], prefill_chunk=4)
    router.health_tick()
    for i in range(3):
        router.generate([1, 2, 3, 4, i], max_new_tokens=1, timeout_s=5)
    text = router.prometheus_metrics()
    for line in text.strip().splitlines():
        assert _PROM_LINE.match(line), f"unparseable line: {line!r}"
    assert f'{ROUTER_REPLICA_UP}{{replica="a"}} 1' in text
    assert f'{ROUTER_REPLICA_UP}{{replica="b"}} 1' in text
    assert f"{ROUTER_REPLICAS_LIVE} 2" in text
    assert f"{ROUTER_AFFINITY_HIT_RATIO} 1" in text
    assert f"{ROUTER_ROUTING_SECONDS}_count 3" in text
    assert 'router_requests_total{replica=' in text


def test_router_http_front_door(stubs):
    """The route CLI's HTTP surface: /generate proxies, fleet-wide 429
    maps with Retry-After, /healthz, /stats and /metrics serve."""
    a, b = stubs("a", "b")
    router = _router([a, b], prefill_chunk=4)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(router))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        def post(payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/generate",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                return r.status, json.loads(r.read().decode())

        status, resp = post({"prompt": [1, 2, 3, 4], "max_new_tokens": 1})
        assert status == 200 and resp["finish_reason"] == "length"
        assert resp["replica"] in ("a", "b")

        status, _ = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=10).status, None
        assert status == 200
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/stats", timeout=10) as r:
            assert json.loads(r.read().decode())["live"] == 2
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert ROUTER_REPLICAS_LIVE in r.read().decode()

        # malformed payload -> 400, fleet saturated -> 429 + Retry-After
        with pytest.raises(urllib.error.HTTPError) as e:
            post({"max_new_tokens": 1})
        assert e.value.code == 400
        a.shed_next = b.shed_next = 10 ** 6
        a.retry_after = b.retry_after = 3
        with pytest.raises(urllib.error.HTTPError) as e:
            post({"prompt": [1, 2, 3, 4], "timeout_s": 0.4})
        assert e.value.code == 429
        assert e.value.headers["Retry-After"] == "3"
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_router_v1_ids_router_unique_and_model_echo(stubs):
    """The router's /v1 front door mints ROUTER-local completion ids —
    two replicas' engine counters count independently (and reset on
    restart), so echoing the replica id would hand two clients the
    same "cmpl-N" — and echoes the fleet's single advertised model
    name when a request names none (matching the serve front door's
    default-model echo), "default" when the fleet is multi-model or
    not yet polled."""
    a, b = stubs("a", "b")
    router = _router([a, b], prefill_chunk=4)
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), make_handler(router))
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        def post(payload):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/completions",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                return json.loads(r.read().decode())

        # nothing advertised yet (no /stats poll has run) -> "default"
        router.health_tick()
        assert post({"prompt": [1, 2, 3, 4],
                     "max_tokens": 1})["model"] == "default"

        # single-model fleet: the one advertised name is the echo, and
        # alternating the serving replica (flip liveness) makes both
        # stub engine counters overlap — router-minted ids must stay
        # unique anyway
        a.models = b.models = ["solo"]
        seen = []
        for i in range(4):
            live, dead = ((a, b) if i % 2 == 0 else (b, a))
            live.healthy, dead.healthy = True, False
            for _ in range(router.eject_after):
                router.health_tick()
            r = post({"prompt": [1, 2, 3, 4], "max_tokens": 1})
            assert r["model"] == "solo"
            seen.append(r["id"])
        assert len(a.received) and len(b.received), "both replicas served"
        assert len(set(seen)) == len(seen), (
            f"/v1 ids must be unique per router process: {seen}")

        # multi-model fleet: ambiguous -> "default"; a named model
        # still echoes itself
        a.healthy = b.healthy = True
        a.models = ["solo", "other"]
        router.health_tick()
        assert post({"prompt": [1, 2, 3, 4],
                     "max_tokens": 1})["model"] == "default"
        assert post({"prompt": [1, 2, 3, 4], "max_tokens": 1,
                     "model": "solo"})["model"] == "solo"
    finally:
        httpd.shutdown()
        httpd.server_close()


# --------------------------------------------------------------------------
# driver side: publish_ports + roll_task against a scripted provisioner
# --------------------------------------------------------------------------

def test_publish_ports_and_budget_free_roll(tmp_job_dirs, tmp_path):
    """The port-advertisement + rolling-restart contract end to end
    against stub executors: a replica publishes named ports (they land
    on get_task_infos, the cluster-spec payload, and the driver
    /metrics), roll_task SIGTERM-drains and relaunches WITHOUT spending
    the restart budget, the relaunch clears the stale ports until the
    new attempt re-publishes, and the executor key cannot roll its
    peers."""
    from tony_tpu.api import JobStatus
    from tony_tpu.conf import TonyConf
    from tony_tpu.driver import Driver
    from tony_tpu.events.trace import TASK_TRACE_FILE, read_traces
    from tony_tpu.rpc import RpcClient
    from tony_tpu.rpc.protocol import RpcError, derive_role_key
    from tests.test_task_trace import ScriptedProvisioner, _rpc_for

    stop_events: dict[str, threading.Event] = {}
    finish = threading.Event()
    acl: dict = {}

    class RollableProvisioner(ScriptedProvisioner):
        def stop_container(self, handle):
            ev = stop_events.get(handle.container_id)
            if ev is not None:
                ev.set()

    def script(spec, index, env, handle, attempt):
        stop_events[handle.container_id] = stopped = threading.Event()
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        payload = rpc.call("register_worker", task_id=task_id,
                           host="127.0.0.1", port=24000 + attempt)
        assert payload is not None      # serving runtime: no gang barrier
        rpc.call("publish_ports", task_id=task_id,
                 ports={"serve_port": 25000 + attempt,
                        "metrics_port": 25000 + attempt})
        # published ports ride the cluster-spec payload
        spec_payload = rpc.call("get_cluster_spec", task_id=task_id)
        assert spec_payload["service_ports"][task_id]["serve_port"] == (
            25000 + attempt)
        if attempt == 0:
            try:        # the executor key must not be able to roll peers
                rpc.call("roll_task", task_id=task_id)
                acl["roll"] = "allowed"
            except RpcError as e:
                acl["roll"] = str(e)
        # beat until the roll stops this attempt / the test finishes
        while not (stopped.is_set() or (attempt > 0 and finish.is_set())):
            rpc.call("heartbeat", task_id=task_id)
            time.sleep(0.05)
        rpc.call("register_execution_result", task_id=task_id, exit_code=0)
        rpc.close()
        return 0

    conf = TonyConf({
        "tony.staging.dir": tmp_job_dirs["staging"],
        "tony.history.location": tmp_job_dirs["history"],
        "tony.history.intermediate": tmp_job_dirs["history"] + "/intermediate",
        "tony.history.finished": tmp_job_dirs["history"] + "/finished",
        "tony.am.monitor-interval-ms": 50,
        "tony.application.framework": "serving",
        "tony.replica.instances": 1,
        "tony.replica.command": "stub",
        "tony.replica.max-restarts": 0,     # a roll must not need budget
        "tony.task.heartbeat-interval-ms": 100,
    })
    job_dir = tmp_path / "job"
    job_dir.mkdir()
    conf.write_final(job_dir)
    driver = Driver(conf, app_id="roll_test", job_dir=str(job_dir),
                    token="roll-secret",
                    provisioner=RollableProvisioner(script))
    driver.client_signal.set()
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    cl = None
    try:
        deadline = time.time() + 20
        while (driver.session.service_ports().get("replica:0", {}).get(
                "serve_port") != 25000 and time.time() < deadline):
            time.sleep(0.02)
        assert driver.session.service_ports() == {
            "replica:0": {"serve_port": 25000, "metrics_port": 25000}}
        infos = {i["name"]: i for i in
                 [t.to_dict() for t in driver.session.task_infos()]}
        assert infos["replica"]["ports"]["serve_port"] == 25000
        text = driver.render_metrics()
        assert ('driver_task_service_port{task="replica:0",'
                'name="serve_port"} 25000') in text
        assert "driver_task_rolls_total 0" in text

        cl = RpcClient("127.0.0.1", driver.rpc_server.port,
                       token=derive_role_key("roll-secret", "client"),
                       role="client")
        assert cl.call("roll_task", task_id="replica:9") is False
        with pytest.raises(RpcError):   # bad port range is rejected
            cl.call("publish_ports", task_id="replica:0",
                    ports={"serve_port": -4})
        assert cl.call("roll_task", task_id="replica:0") is True
        deadline = time.time() + 20
        while (driver.session.service_ports().get("replica:0", {}).get(
                "serve_port") != 25001 and time.time() < deadline):
            time.sleep(0.02)
        # attempt 1 is up with fresh ports; the roll spent no budget
        assert driver.session.service_ports()["replica:0"][
            "serve_port"] == 25001
        assert driver.provisioner.launches == ["replica:0"] * 2
        text = driver.render_metrics()
        assert "driver_task_rolls_total 1" in text
        assert "driver_task_restarts_total 0" in text
    finally:
        finish.set()
        if cl is not None:
            cl.close()
    t.join(timeout=30)
    assert not t.is_alive(), "driver did not finish"
    assert driver.session.status == JobStatus.SUCCEEDED, (
        driver.session.failure_message)
    assert "authorization" in acl["roll"], acl
    from pathlib import Path

    recs = read_traces(Path(tmp_job_dirs["history"]) / "intermediate"
                       / "roll_test" / TASK_TRACE_FILE)
    assert len(recs) == 1
    names = [n for n, _ in recs[0]["spans"]]
    assert names.count("rolled") == 1 and "restarted" not in names
    assert names.count("registered") == 2       # both attempts in one trace
    assert names[-1] == "finished"
    assert recs[0]["attrs"]["restarts"] == 0
    assert recs[0]["attrs"]["ports"]["serve_port"] == 25001


def test_discovery_sync_moves_and_drops_replicas(stubs):
    """sync_replicas: a restarted replica re-points under its task_id, a
    vanished one leaves rotation, a new one joins."""
    a, b = stubs("a", "b")
    router = FleetRouter(
        [], prefill_chunk=4, seed=0,
        discover=lambda: [("replica:0", "127.0.0.1", a.port)])
    router.health_tick()
    assert router.stats()["replicas"]["replica:0"]["endpoint"].endswith(
        str(a.port))
    # the task restarts at a new port; same identity, new endpoint
    router.discover = lambda: [("replica:0", "127.0.0.1", b.port)]
    router.health_tick()
    st = router.stats()["replicas"]
    assert list(st) == ["replica:0"]
    assert st["replica:0"]["endpoint"].endswith(str(b.port))
    router.generate([1, 2, 3, 4], max_new_tokens=1, timeout_s=5)
    assert len(b.received) == 1 and not a.received
    # mid-restart the driver clears ports — but an EMPTY fleet while the
    # replica still answers its own probes is DISTRUSTED for the
    # discovery grace (a dead/recovering driver must not drop a serving
    # fleet; ISSUE 12), then honored once the driver insists
    router.discover = lambda: []
    router.discovery_grace_s = 0.05
    router.health_tick()
    st = router.stats()
    assert st["discovery_stale"] is True
    assert list(st["replicas"]) == ["replica:0"]
    time.sleep(0.06)
    router.health_tick()
    assert router.stats()["replicas"] == {}
    assert router.stats()["discovery_stale"] is False


# --------------------------------------------------------------------------
# acceptance e2e: real fleet, byte-identical burst, mid-burst replica kill
# --------------------------------------------------------------------------

# one TINY shape shared by the replica serve processes (CLI flags) and
# the in-process solo reference server
_E2E = dict(vocab=64, d_model=16, n_layers=1, n_heads=2, d_ff=32,
            slots=2, max_len=64, block_size=4, prefill_chunk=8)


def test_fleet_e2e_kill_midburst_zero_failures(tmp_job_dirs, tmp_path):
    """The fleet acceptance contract: the driver gang-launches 2 TINY
    SlotServer replicas (serving job type — real serve processes found
    via publish_ports + driver discovery), the router completes a paced
    burst with results byte-identical to a solo in-process server, one
    replica is SIGKILLed mid-burst, and the combination of router retry
    + budgeted driver restart turns the kill into latency: zero failed
    requests, the replica returns at a new port, and the fleet is whole
    again."""
    import os
    import signal
    import sys

    import jax
    import numpy as np

    from tony_tpu.cluster.provisioner import LocalProvisioner
    from tony_tpu.conf import TonyConf
    from tony_tpu.driver import Driver
    from tony_tpu.models import transformer
    from tony_tpu.models.serving import Request, SlotServer

    e = _E2E
    serve_cmd = (
        f"{sys.executable} -m tony_tpu.cli.main serve "
        "--port $TONY_SERVE_PORT --host 127.0.0.1 "
        f"--vocab {e['vocab']} --d-model {e['d_model']} "
        f"--n-layers {e['n_layers']} --n-heads {e['n_heads']} "
        f"--d-ff {e['d_ff']} --dtype float32 --seed 0 "
        f"--slots {e['slots']} --max-len {e['max_len']} "
        f"--block-size {e['block_size']} "
        f"--prefill-chunk {e['prefill_chunk']} "
        "--max-queue 32 --drain-timeout-s 2")
    import tests.conftest as _conftest

    conf = TonyConf({
        "tony.staging.dir": tmp_job_dirs["staging"],
        "tony.history.location": tmp_job_dirs["history"],
        "tony.history.intermediate": tmp_job_dirs["history"] + "/intermediate",
        "tony.history.finished": tmp_job_dirs["history"] + "/finished",
        "tony.am.monitor-interval-ms": 100,
        "tony.application.framework": "serving",
        "tony.replica.instances": 2,
        "tony.replica.command": serve_cmd,
        "tony.replica.max-restarts": 1,     # the kill spends exactly one
        "tony.serving.healthz-interval-ms": 200,
        "tony.task.heartbeat-interval-ms": 250,
        # children must find the package and stay on CPU regardless of
        # how pytest was invoked
        "tony.execution.env": [
            f"PYTHONPATH={_conftest.REPO_ROOT}", "JAX_PLATFORMS=cpu"],
    })
    job_dir = tmp_path / "job"
    job_dir.mkdir()
    conf.write_final(job_dir)
    driver = Driver(conf, app_id="fleet_e2e", job_dir=str(job_dir),
                    token="fleet-secret", provisioner=LocalProvisioner())
    driver.client_signal.set()
    driver_thread = threading.Thread(target=driver.run, daemon=True)
    driver_thread.start()

    discovery = DriverDiscovery(str(job_dir), role="replica",
                                token="fleet-secret")
    router = FleetRouter([], prefill_chunk=e["prefill_chunk"],
                         discover=discovery, health_interval_s=0.3,
                         eject_after=1, seed=0)

    # the reference results: a solo in-process server over the SAME
    # params (seed-0 random init, greedy) serving the same prompts
    cfg = transformer.TransformerConfig(
        vocab_size=e["vocab"], d_model=e["d_model"],
        n_layers=e["n_layers"], n_heads=e["n_heads"],
        n_kv_heads=e["n_heads"], d_ff=e["d_ff"], dtype=jax.numpy.float32)
    params = transformer.init(jax.random.PRNGKey(0), cfg)
    rng = np.random.default_rng(11)
    chunk = e["prefill_chunk"]
    templates = [rng.integers(0, e["vocab"], size=chunk, dtype=np.int32),
                 rng.integers(0, e["vocab"], size=2 * chunk,
                              dtype=np.int32)]
    prompts = [
        np.concatenate([templates[i % 2],
                        rng.integers(0, e["vocab"], size=1 + i % 3,
                                     dtype=np.int32)]).tolist()
        for i in range(10)
    ]
    max_new = 4
    solo = SlotServer(params, cfg, slots=e["slots"], max_len=e["max_len"],
                      block_size=e["block_size"], prefill_chunk=chunk,
                      temperature=0.0, seed=0)
    reqs = [Request(prompt=np.asarray(p, dtype=np.int32),
                    max_new_tokens=max_new) for p in prompts]
    for r in reqs:
        solo.submit(r)
    done = solo.run_until_drained()
    expected = {i: done[r.id].tokens for i, r in enumerate(reqs)}
    solo.shutdown()

    results: dict[int, object] = {}
    killed: dict = {}
    try:
        # both replicas serving (ports published after first healthy
        # /healthz) — generous deadline: two jax imports + tiny compiles
        # on a 2-core host
        deadline = time.time() + 150
        while time.time() < deadline:
            router.health_tick()
            if router.stats()["live"] == 2:
                break
            time.sleep(0.3)
        assert router.stats()["live"] == 2, (
            f"fleet never came up: {router.stats()}")
        router.start()

        def call(i):
            try:
                results[i] = router.generate(
                    prompts[i], max_new_tokens=max_new, timeout_s=120)
            except Exception as exc:    # pragma: no cover - the failure
                results[i] = exc        # the assertion below reports

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(prompts))]
        # two-phase burst so the kill deterministically lands MID-burst:
        # phase 1 proves the fleet serves, then one replica dies, then
        # the rest of the burst arrives against the degraded fleet
        for t in threads[:5]:
            t.start()
            time.sleep(0.05)
        deadline = time.time() + 120
        while (sum(isinstance(r, dict) for r in results.values()) < 3
               and time.time() < deadline):
            time.sleep(0.1)
        first = next((r for r in results.values() if isinstance(r, dict)),
                     None)
        assert first is not None, f"phase 1 never completed: {results}"
        # hard-kill the replica that served the first completion
        victim = first["replica"]
        ep = router.stats()["replicas"][victim]["endpoint"]
        with urllib.request.urlopen(f"http://{ep}/stats",
                                    timeout=10) as resp:
            pid = json.loads(resp.read().decode())["pid"]
        os.kill(pid, signal.SIGKILL)
        killed["task"] = victim
        for t in threads[5:]:
            t.start()
            time.sleep(0.05)
        for t in threads:
            t.join(timeout=150)
        assert not any(t.is_alive() for t in threads), "a waiter hung"

        # ZERO failed requests: every result is a completion...
        failures = {i: r for i, r in results.items()
                    if not isinstance(r, dict)}
        assert not failures, f"requests failed across the kill: {failures}"
        # ... and every completion is byte-identical to the solo server
        for i, r in sorted(results.items()):
            assert r["tokens"] == expected[i], (
                f"request {i} diverged: {r['tokens']} vs {expected[i]} "
                f"(served by {r['replica']})")

        # the kill cost the router visible work (a retry or an ejection)
        st = router.stats()
        assert (sum(rep["errors"] + rep["retries"]
                    for rep in st["replicas"].values()) >= 1), st

        # ... and the driver a budgeted restart; the replica comes back
        # at a NEW port and the fleet is whole again
        deadline = time.time() + 150
        while time.time() < deadline:
            st = router.stats()
            if st["live"] == 2 and killed["task"] in st["replicas"]:
                break
            time.sleep(0.5)
        assert router.stats()["live"] == 2, (
            f"killed replica never rejoined: {router.stats()}")
        assert "driver_task_restarts_total 1" in driver.render_metrics()
        # the restarted replica serves its template again
        tail = router.generate(prompts[0], max_new_tokens=max_new,
                               timeout_s=120)
        assert tail["tokens"] == expected[0]
    finally:
        router.shutdown()
        discovery.close()
        driver.session.kill_all("test complete")
        driver_thread.join(timeout=60)
    assert not driver_thread.is_alive(), "driver did not stop"


# --------------------------------------------------------------------------
# disaggregated serving: phase-aware routing (PR 17)
# --------------------------------------------------------------------------


def test_disagg_two_leg_handoff(stubs):
    """The disaggregated happy path: a roled fleet routes the request
    through TWO legs — prefill on the specialist, then the handoff
    payload POSTed VERBATIM to the decode replica's /kv/import — and
    the caller sees one completion, served by the decode leg."""
    pre, dec = stubs("pre", "dec")
    pre.role, dec.role = "prefill", "decode"
    pre.handoff = {"version": 1, "entry": {"prompt": [1, 2, 3]}}
    router = _router([pre, dec], prefill_chunk=8)
    router.health_tick()
    assert router.replicas["pre"].role == "prefill"
    assert router.replicas["dec"].role == "decode"

    resp = router.generate([1, 2, 3], max_new_tokens=4, timeout_s=5)
    base = sum([1, 2, 3]) % 100
    assert resp["tokens"] == [base + 1, base + 2]
    assert resp["replica"] == "dec"
    assert resp["prefill_replica"] == "pre"
    assert pre.received == [[1, 2, 3]], "leg 1 must hit the specialist"
    assert dec.import_payloads == [pre.handoff], \
        "leg 2 must carry the handoff payload verbatim"
    assert not dec.received, "decode leg rides /kv/import, not /generate"
    st = router.stats()
    assert (st["disagg_requests"], st["disagg_handoffs"],
            st["disagg_fallbacks"]) == (1, 1, 0)
    assert st["failed"] == 0
    # per-role aggregates feed the two-tier autoscaler
    assert st["fleet"]["roles"]["prefill"]["live"] == 1
    assert st["fleet"]["roles"]["decode"]["live"] == 1
    # ... and the three counters render on /metrics
    text = router.prometheus_metrics()
    for fam in ("router_disagg_requests_total",
                "router_disagg_handoffs_total",
                "router_disagg_fallbacks_total"):
        assert f"{fam} " in text, fam
    for line in text.strip().splitlines():
        assert _PROM_LINE.match(line), f"malformed line: {line!r}"


def test_disagg_prefill_replicas_never_serve_classic(stubs):
    """A prefill specialist is reachable ONLY through the two-leg path:
    when it sheds its leg, the fallback re-prefills on the decode-
    capable replica — the specialist never appears in the classic
    rotation, and no request fails."""
    pre, both = stubs("pre", "both")
    pre.role = "prefill"                     # 'both' stays roleless
    pre.shed_next = 10                       # every prefill leg sheds
    router = _router([pre, both], prefill_chunk=8)
    router.health_tick()
    for i in range(3):
        resp = router.generate([7, i], max_new_tokens=2, timeout_s=5)
        assert resp["replica"] == "both"
    st = router.stats()
    assert (st["disagg_requests"], st["disagg_handoffs"],
            st["disagg_fallbacks"]) == (3, 0, 3)
    assert st["failed"] == 0
    assert len(both.received) == 3, \
        "fallback = classic single-leg re-prefill from the prompt"
    assert not pre.import_payloads and len(pre.received) == 0


def test_disagg_fallback_on_torn_import(stubs):
    """A damaged payload is rejected LOUDLY by the decode replica (400
    from import_blocks) and the router replays: re-prefill from the
    prompt on the classic path. Recompute, never a lost request."""
    pre, dec = stubs("pre", "dec")
    pre.role, dec.role = "prefill", "decode"
    pre.handoff = {"version": 1, "entry": {"prompt": [4, 4]}}
    dec.client_error_next = 1                # 400s the /kv/import POST
    router = _router([pre, dec], prefill_chunk=8)
    router.health_tick()
    resp = router.generate([4, 4], max_new_tokens=2, timeout_s=5)
    assert resp["replica"] == "dec"
    assert resp["finish_reason"] == "length"
    st = router.stats()
    assert (st["disagg_handoffs"], st["disagg_fallbacks"]) == (0, 1)
    assert st["failed"] == 0
    assert router.replicas["dec"].up, "a torn payload must not eject"
    assert len(dec.received) == 1, "fallback re-prefilled on dec"


def test_disagg_stale_export_and_stale_role(stubs):
    """Two advertisement-skew shapes: (a) the specialist prefilled but
    its export stash aged out (no handoff in the response) — fall back;
    (b) a replica advertised prefill but served the WHOLE request
    (role changed between polls) — deliver what we already paid for."""
    pre, dec = stubs("pre", "dec")
    pre.role, dec.role = "prefill", "decode"
    pre.handoff = None                       # (a) stash aged out
    router = _router([pre, dec], prefill_chunk=8)
    router.health_tick()
    resp = router.generate([5, 6], max_new_tokens=2, timeout_s=5)
    assert resp["finish_reason"] == "length"
    st = router.stats()
    assert (st["disagg_handoffs"], st["disagg_fallbacks"]) == (0, 1)
    assert st["failed"] == 0
    # (b): the "specialist" stops advertising prefilled terminals —
    # emulate by clearing the role on the stub side only (the router
    # still believes it's a specialist until the next poll)
    pre.role = None                          # serves a full completion
    resp = router.generate([6, 7], max_new_tokens=2, timeout_s=5)
    assert resp["replica"] == "pre"
    assert resp["tokens"] == [2], "the full completion is delivered"
    assert router.stats()["failed"] == 0


def test_disagg_mixed_fleet_degrades_to_classic(stubs):
    """A fleet with NO live prefill specialist (roleless or role=both)
    never attempts the two-leg path — today's behavior, untouched."""
    a, b = stubs("a", "b")
    b.role = "both"
    router = _router([a, b], prefill_chunk=8)
    router.health_tick()
    for i in range(4):
        router.generate([9, i], max_new_tokens=1, timeout_s=5)
    st = router.stats()
    assert st["disagg_requests"] == 0
    assert st["failed"] == 0
    assert len(a.received) + len(b.received) == 4
