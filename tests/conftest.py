"""Test harness setup: force JAX onto CPU with 8 virtual devices so the whole
suite (sharding, mesh, collectives, e2e) runs without TPU hardware — the
TPU-native analogue of the reference's in-process MiniCluster test strategy
(tony-mini/.../MiniCluster.java:43-65, TestTonyE2E.java:90-109)."""

import os
import sys
from pathlib import Path

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import jax  # noqa: E402

# this environment's sitecustomize registers the axon TPU plugin and forces
# jax_platforms="axon,cpu" via jax.config, which overrides the env var —
# override it back before any backend initialization
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# ---------------------------------------------------------------- watchdog
# Per-test watchdog: a HUNG test (a serving-loop deadlock, a waiter that
# never wakes) must fail fast with a stack trace of every thread instead of
# silently eating the tier-1 gate's whole 870s budget. faulthandler's timer
# dumps all thread stacks and hard-exits the process — blunt, but a hang
# has no cooperative way out, and the dump names the guilty frame.
# Budget: TONY_TEST_WATCHDOG_S env (0 disables); @pytest.mark.slow tests
# (compile-bound, excluded from tier-1) get 3x.

import faulthandler  # noqa: E402

try:
    _WATCHDOG_S = float(os.environ.get("TONY_TEST_WATCHDOG_S", "300"))
except ValueError:      # bad knob degrades to the default, never aborts
    _WATCHDOG_S = 300.0


def _watchdog_budget(item) -> float:
    if _WATCHDOG_S <= 0:
        return 0.0
    mult = 3.0 if item.get_closest_marker("slow") else 1.0
    return _WATCHDOG_S * mult


@pytest.hookimpl(tryfirst=True)
def pytest_runtest_setup(item):
    # tryfirst: arm before the runner starts fixture setup, so a hang
    # INSIDE a fixture is covered too
    budget = _watchdog_budget(item)
    if budget > 0:
        faulthandler.dump_traceback_later(budget, exit=True)


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_teardown(item, nextitem):
    # wrapper: the watchdog stays armed THROUGH fixture finalizers (a
    # hang in e.g. a deadlocked ServeApp.shutdown is covered) and the
    # finally-cancel runs even when a finalizer raises — a plain trylast
    # impl would be skipped by the re-raise, leaving the hard-exit timer
    # live into session teardown
    try:
        yield
    finally:
        if _WATCHDOG_S > 0:
            faulthandler.cancel_dump_traceback_later()


# ------------------------------------------------------------- env flakes
# @pytest.mark.env_flaky — ONE automatic rerun on failure. Reserved for
# tests whose failures are a known ENVIRONMENT flake, identical on an
# unmodified checkout (the container's jax CPU gloo-collective
# availability comes and goes across the day — ROADMAP "known flakes");
# a genuine regression still fails both attempts and reports normally.
# Only the final attempt's reports are logged, so pass counts stay
# honest (one dot per test either way).

@pytest.hookimpl(tryfirst=True)
def pytest_runtest_protocol(item, nextitem):
    if item.get_closest_marker("env_flaky") is None:
        return None
    from _pytest.runner import runtestprotocol

    item.ihook.pytest_runtest_logstart(nodeid=item.nodeid,
                                       location=item.location)
    reports = runtestprotocol(item, nextitem=nextitem, log=False)
    if any(r.failed for r in reports):
        print(f"\n[env_flaky] {item.nodeid} failed; rerunning once "
              "(known environment flake)", flush=True)
        # drop the first attempt's (already-finalized) fixture instances
        # so the rerun gets FRESH setup — _fillfixtures skips argnames
        # already present in item.funcargs, which would otherwise hand
        # the retry stale tmp dirs (pytest-rerunfailures does the same)
        if hasattr(item, "_initrequest"):
            item._initrequest()
        reports = runtestprotocol(item, nextitem=nextitem, log=False)
    for report in reports:
        item.ihook.pytest_runtest_logreport(report=report)
    item.ihook.pytest_runtest_logfinish(nodeid=item.nodeid,
                                        location=item.location)
    return True


# ------------------------------------------------------ tier-1 wall budget
# Per-test call durations, collected for the wall-budget guard
# (tests/test_budget_lint.py): a single non-slow test creeping past the
# per-test ceiling is how the 870s tier-1 gate historically overflowed
# (ROADMAP "budget is VERY thin"), and this surfaces the offender by
# NAME instead of as a mysterious whole-gate timeout. The lint test is
# reordered to run LAST so it sees every test of the session; durations
# cover the call phase (fixtures excluded — parallel to --durations).

TEST_DURATIONS: dict[str, float] = {}
SLOW_NODEIDS: set[str] = set()


@pytest.hookimpl
def pytest_runtest_logreport(report):
    if report.when == "call":
        TEST_DURATIONS[report.nodeid] = report.duration


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.get_closest_marker("slow"):
            SLOW_NODEIDS.add(item.nodeid)
    tail = [i for i in items if "test_tier1_wall_budget" in i.nodeid]
    if tail:
        head = [i for i in items if "test_tier1_wall_budget" not in i.nodeid]
        items[:] = head + tail


@pytest.fixture
def tmp_job_dirs(tmp_path):
    """Staging + history dirs for orchestration tests."""
    staging = tmp_path / "staging"
    history = tmp_path / "history"
    staging.mkdir()
    history.mkdir()
    return {"staging": str(staging), "history": str(history)}


FIXTURE_SCRIPTS = REPO_ROOT / "tests" / "fixtures" / "scripts"


@pytest.fixture
def fixture_script():
    def _get(name: str) -> str:
        path = FIXTURE_SCRIPTS / name
        assert path.exists(), f"missing fixture script {name}"
        return str(path)

    return _get
