"""Test harness setup: force JAX onto CPU with 8 virtual devices so the whole
suite (sharding, mesh, collectives, e2e) runs without TPU hardware — the
TPU-native analogue of the reference's in-process MiniCluster test strategy
(tony-mini/.../MiniCluster.java:43-65, TestTonyE2E.java:90-109)."""

import os
import sys
from pathlib import Path

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

import jax  # noqa: E402

# this environment's sitecustomize registers the axon TPU plugin and forces
# jax_platforms="axon,cpu" via jax.config, which overrides the env var —
# override it back before any backend initialization
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def tmp_job_dirs(tmp_path):
    """Staging + history dirs for orchestration tests."""
    staging = tmp_path / "staging"
    history = tmp_path / "history"
    staging.mkdir()
    history.mkdir()
    return {"staging": str(staging), "history": str(history)}


FIXTURE_SCRIPTS = REPO_ROOT / "tests" / "fixtures" / "scripts"


@pytest.fixture
def fixture_script():
    def _get(name: str) -> str:
        path = FIXTURE_SCRIPTS / name
        assert path.exists(), f"missing fixture script {name}"
        return str(path)

    return _get
