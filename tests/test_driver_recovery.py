"""Control-plane journal + driver recovery (ISSUE 12, docs/
training-robustness.md "Control-plane recovery").

The contract under test: the driver journals its authoritative state to
``driver.journal.jsonl`` (append+flush, torn-line-tolerant read,
tmp+rename compaction), a replacement driver (``Driver.recover`` /
``tony-tpu driver --recover``) replays it, rewrites driver.json with a
bumped ``driver_generation``, and RE-ADOPTS live tasks — surviving
executors' heartbeats re-attach by task id + attempt, zombie
registrations from superseded attempts are refused by the attempt
fence, and dead-while-orphaned tasks relaunch under the journaled
restart budget. The edges tolerate the outage instead of amplifying
it: the Heartbeater rides a bounded grace window (re-resolving the
recovered driver's endpoint from driver.json, without inflating
``heartbeats_missed``), and the fleet router keeps serving its
last-known fleet while discovery is blind (``router_discovery_stale``).

Stub executors are threads speaking the real framed-JSON RPC (the
test_task_trace pattern) that deliberately SURVIVE the first driver's
death and re-resolve driver.json — exactly what a real executor's
outage-grace path does — so the whole recovery cycle runs in ~seconds.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from pathlib import Path

import pytest

import tony_tpu.constants as c
from tony_tpu.api import JobStatus
from tony_tpu.cluster.provisioner import ContainerHandle, Provisioner
from tony_tpu.conf import TonyConf
from tony_tpu.driver import Driver
from tony_tpu.events.driver_journal import (
    DriverJournal,
    load_state,
    rewrite_journal,
)
from tony_tpu.events.trace import TASK_TRACE_FILE, read_traces
from tony_tpu.rpc import RpcClient, RpcError


# --------------------------------------------------------------------------
# harness (test_task_trace pattern, death-surviving variant)
# --------------------------------------------------------------------------

def _conf(dirs, **extra):
    return TonyConf({
        "tony.staging.dir": dirs["staging"],
        "tony.history.location": dirs["history"],
        "tony.history.intermediate": dirs["history"] + "/intermediate",
        "tony.history.finished": dirs["history"] + "/finished",
        "tony.am.monitor-interval-ms": 50,
        "tony.task.registration-poll-interval-ms": 50,
        **extra,
    })


class ScriptedProvisioner(Provisioner):
    """launch() runs ``script(spec, index, env, handle, attempt)`` on a
    thread; a script returning None reports no container completion
    (the adopted-handle situation: the spawning driver is dead)."""

    def __init__(self, script):
        super().__init__()
        self._script = script
        self._attempts: dict[str, int] = {}
        self._lock = threading.Lock()
        self.launches: list[str] = []

    def launch(self, spec, index, env, log_dir):
        task_id = f"{spec.name}:{index}"
        with self._lock:
            attempt = self._attempts.get(task_id, 0)
            self._attempts[task_id] = attempt + 1
            self.launches.append(task_id)
        handle = ContainerHandle(
            container_id=f"stub_{task_id}_{attempt}",
            host="127.0.0.1", role=spec.name, index=index,
        )
        threading.Thread(
            target=self._run, args=(spec, index, env, handle, attempt),
            daemon=True,
        ).start()
        return handle

    def _run(self, spec, index, env, handle, attempt):
        try:
            code = self._script(spec, index, env, handle, attempt)
        except Exception as e:                  # pragma: no cover - debug aid
            print(f"stub executor failed: {type(e).__name__}: {e}",
                  flush=True)
            code = 1
        if code is not None and self.on_completion:
            self.on_completion(handle, code)

    def stop_container(self, handle):
        pass

    def stop_all(self):
        pass


def _make_driver(dirs, job_dir, script, **conf_extra):
    conf = _conf(dirs, **conf_extra)
    job_dir.mkdir(exist_ok=True)
    conf.write_final(job_dir)
    driver = Driver(conf, app_id="recover_test", job_dir=str(job_dir),
                    token="recover-secret",
                    provisioner=ScriptedProvisioner(script))
    driver.client_signal.set()      # no client: don't wait for the ack
    return driver


def _abrupt_death(driver, thread):
    """Simulate driver death for the in-process tests: stop the monitor
    loop and tear the RPC endpoint down WITHOUT completing any task —
    exactly the state a SIGKILL leaves behind (live executors, live
    journal, no terminal records). The scripted provisioner's stops are
    no-ops, so no container is touched, and the callbacks are
    disconnected so the corpse can't react to late completions."""
    driver._stop_requested.set()
    thread.join(timeout=20)
    assert not thread.is_alive(), "first driver did not wind down"
    driver.provisioner.on_completion = None
    # a SIGKILL severs established RPC connections, but the in-process
    # stand-in can't kill the corpse's lingering per-connection handler
    # threads (ThreadingTCPServer.shutdown only stops the accept loop) —
    # make them REFUSE instead, so persistent clients fail over to the
    # recovered endpoint exactly as they would on a reset connection
    driver.rpc_server._handlers.clear()


def _resolving_stub(job_dir, release, ports_base=22000, exit_code=0,
                    hold=None):
    """A death-surviving stub executor: registers (echoing its launch
    attempt), heartbeats, and on ANY transport failure re-resolves the
    driver endpoint from driver.json — the thread-stub equivalent of the
    executor's outage-grace path. Reports exit over the RPC once
    ``release`` is set. Returns None so the scripted provisioner never
    reports a container completion (the first driver is dead by then;
    the recovered driver treats the executor report as authoritative)."""

    def stub(spec, index, env, handle, attempt):
        task_id = f"{spec.name}:{index}"
        if hold is not None and not hold.wait(30):
            return None

        def fresh_client():
            info = json.loads(
                (job_dir / c.DRIVER_INFO_FILE).read_text())
            return RpcClient(info["host"], info["port"],
                             token=env[c.ENV_TOKEN], role="executor",
                             max_retries=1)

        rpc = fresh_client()
        payload = rpc.call(
            "register_worker", task_id=task_id, host="127.0.0.1",
            port=ports_base + index,
            attempt=int(env[c.ENV_TASK_ATTEMPT]))
        deadline = time.time() + 30
        while payload is None and time.time() < deadline:
            time.sleep(0.05)
            payload = rpc.call("get_cluster_spec", task_id=task_id)
        while not release.is_set() and time.time() < deadline:
            try:
                rpc.call("heartbeat", task_id=task_id)
            except Exception:
                rpc.close()
                time.sleep(0.05)
                try:
                    rpc = fresh_client()
                except Exception:
                    pass
            time.sleep(0.05)
        for _ in range(100):
            try:
                rpc.call("register_execution_result", task_id=task_id,
                         exit_code=exit_code)
                break
            except Exception:
                rpc.close()
                time.sleep(0.1)
                try:
                    rpc = fresh_client()
                except Exception:
                    pass
        rpc.close()
        return None

    return stub


def _last_trace_per_id(path):
    recs = {}
    for rec in read_traces(path):
        recs[rec["id"]] = rec       # later records win (recovery appends)
    return recs


# --------------------------------------------------------------------------
# journal unit: replay, torn lines, compaction
# --------------------------------------------------------------------------

def test_journal_replay_roundtrip(tmp_path):
    """Every op kind replays; a new launch clears the old attempt's
    registration/ports/ledgers; meta takes last-wins."""
    p = tmp_path / "driver.journal.jsonl"
    j = DriverJournal(p)
    j.record("meta", app_id="app1", token="tok", session_id=0,
             rpc_port=41001, driver_generation=0)
    j.record("launch", task="worker:0", attempt=1, container_id="c0",
             pid=111, host="h0", t=10.0, log_path="l0")
    j.record("register", task="worker:0", host="h0", port=9001)
    j.record("ports", task="worker:0", ports={"serve_port": 8080})
    j.record("ledger", kind="preempt", task="worker:0", cmd=True)
    j.record("restarts", task="worker:0", used=1)
    j.record("launch", task="worker:0", attempt=2, container_id="c1",
             pid=112, host="h0", t=20.0, log_path="l1")
    j.record("launch", task="worker:1", attempt=1, container_id="c2",
             pid=113, host="h1", t=11.0, log_path="l2")
    j.record("register", task="worker:1", host="h1", port=9002)
    j.record("terminal", task="worker:1", status="SUCCEEDED", exit_code=0)
    j.record("generation", gen=3)
    j.record("detach", task="worker:2")
    j.record("meta", app_id="app1", token="tok", session_id=0,
             rpc_port=41002, driver_generation=1)
    j.close()

    s = load_state(p)
    assert s is not None
    assert (s.app_id, s.token, s.rpc_port) == ("app1", "tok", 41002)
    assert s.driver_generation == 1 and s.gang_generation == 3
    w0 = s.tasks["worker:0"]
    # the second launch superseded everything the first attempt was
    assert w0.attempt == 2 and w0.pid == 112 and w0.restarts == 1
    assert not w0.registered and w0.ports == {} and not w0.terminal
    assert "worker:0" not in s.preempts
    w1 = s.tasks["worker:1"]
    assert w1.terminal and w1.status == "SUCCEEDED" and w1.exit_code == 0
    assert s.detached == {"worker:2"}


def test_journal_torn_line_and_missing_meta(tmp_path):
    """A record torn by SIGKILL mid-write is dropped, not fatal; a file
    with no meta record (or no file at all) is not recoverable."""
    p = tmp_path / "driver.journal.jsonl"
    j = DriverJournal(p)
    j.record("meta", app_id="app1", token="t", session_id=0,
             rpc_port=1, driver_generation=0)
    j.record("launch", task="worker:0", attempt=1, container_id="c0",
             pid=1, host="h", t=1.0)
    j.close()
    with open(p, "a") as f:
        f.write('{"op": "launch", "task": "worker:1", "atte')   # torn
    s = load_state(p)
    assert s is not None and list(s.tasks) == ["worker:0"]

    assert load_state(tmp_path / "nope.jsonl") is None
    metaless = tmp_path / "metaless.jsonl"
    metaless.write_text(
        '{"op": "launch", "task": "worker:0", "attempt": 1}\n')
    assert load_state(metaless) is None


def test_journal_rewrite_compacts_to_live_state(tmp_path):
    """rewrite_journal collapses an op stream down to its replayed
    state (tmp+rename) and the compacted file replays identically."""
    p = tmp_path / "driver.journal.jsonl"
    j = DriverJournal(p)
    j.record("meta", app_id="a", token="t", session_id=0, rpc_port=5,
             driver_generation=0)
    for attempt in range(1, 21):
        j.record("launch", task="worker:0", attempt=attempt,
                 container_id=f"c{attempt}", pid=100 + attempt, host="h",
                 t=float(attempt))
        j.record("register", task="worker:0", host="h", port=9000)
    j.close()
    before = load_state(p)
    assert len(p.read_text().splitlines()) == 41
    rewrite_journal(p, before)
    after = load_state(p)
    assert len(p.read_text().splitlines()) == 3     # meta+launch+register
    assert after.tasks["worker:0"].attempt == 20
    assert after.tasks["worker:0"].registered
    assert after.tasks["worker:0"].pid == 120


# --------------------------------------------------------------------------
# attempt fence: zombie registrations refused
# --------------------------------------------------------------------------

def test_register_worker_refuses_stale_attempt(tmp_job_dirs, tmp_path):
    """A superseded attempt's executor (zombie from before a recovery /
    restart) registering with its old attempt ordinal is refused; the
    current attempt — and fence-less legacy callers (attempt=-1) —
    register fine."""
    release = threading.Event()
    job_dir = tmp_path / "job"
    envs = {}

    def stub(spec, index, env, handle, attempt):
        envs[attempt] = dict(env)
        release.wait(20)
        return None

    driver = _make_driver(
        tmp_job_dirs, job_dir, stub,
        **{"tony.worker.instances": 1, "tony.worker.command": "stub"})
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    try:
        deadline = time.time() + 10
        while 0 not in envs and time.time() < deadline:
            time.sleep(0.05)
        env = envs[0]
        assert env[c.ENV_TASK_ATTEMPT] == "1"
        assert env[c.ENV_DRIVER_GENERATION] == "0"
        rpc = RpcClient(env[c.ENV_DRIVER_HOST], int(env[c.ENV_DRIVER_PORT]),
                        token=env[c.ENV_TOKEN], role="executor")
        with pytest.raises(RpcError, match="stale attempt"):
            rpc.call("register_worker", task_id="worker:0",
                     host="127.0.0.1", port=23000, attempt=0)
        # the real attempt and a legacy (fence-less) caller both pass
        assert rpc.call("register_worker", task_id="worker:0",
                        host="127.0.0.1", port=23000, attempt=1) is not None
        assert rpc.call("register_worker", task_id="worker:0",
                        host="127.0.0.1", port=23000) is not None
        rpc.close()
    finally:
        release.set()
        driver._stop_requested.set()
        t.join(timeout=20)


# --------------------------------------------------------------------------
# the core: recovery re-adopts live workers, zero extra restarts
# --------------------------------------------------------------------------

def test_recover_readopts_live_stub_workers(tmp_job_dirs, tmp_path):
    """Driver #1 launches 2 workers and dies abruptly mid-job (no
    terminal records, executors alive). Driver.recover() replays the
    journal, bumps driver_generation in driver.json, re-adopts both
    workers (readopted spans + driver_tasks_readopted_total), their
    heartbeats re-attach through the rewritten driver.json, the job
    finishes SUCCEEDED with ZERO task restarts and zero relaunches, and
    the journal was compacted on the way."""
    release = threading.Event()
    job_dir = tmp_path / "job"
    stub = _resolving_stub(job_dir, release)

    d1 = _make_driver(
        tmp_job_dirs, job_dir, stub,
        **{"tony.worker.instances": 2, "tony.worker.command": "stub",
           "tony.worker.max-restarts": 1,
           "tony.task.heartbeat-interval-ms": 100})
    t1 = threading.Thread(target=d1.run, daemon=True)
    t1.start()
    deadline = time.time() + 15
    while d1.session.registered_count() < 2 and time.time() < deadline:
        time.sleep(0.05)
    assert d1.session.registered_count() == 2, "workers never registered"
    journal_lines = (job_dir / c.DRIVER_JOURNAL_FILE).read_text()
    assert '"op": "launch"' in journal_lines
    _abrupt_death(d1, t1)

    # ---- recovery: a provisioner whose launch() would flag the bug
    relaunches = []

    def must_not_launch(spec, index, env, handle, attempt):
        relaunches.append(f"{spec.name}:{index}")
        return 1

    d2 = Driver.recover(str(job_dir),
                        provisioner=ScriptedProvisioner(must_not_launch))
    d2.client_signal.set()
    assert d2._recoveries == 1 and d2._readopted == 2
    assert d2.driver_generation == 1
    assert dict(d2._attempts) == {"worker:0": 1, "worker:1": 1}
    t2 = threading.Thread(target=d2.run, daemon=True)
    t2.start()
    try:
        # the rewritten driver.json is what the stubs re-resolve
        deadline = time.time() + 15
        info = {}
        while time.time() < deadline:
            info = json.loads((job_dir / c.DRIVER_INFO_FILE).read_text())
            if info.get("pid") == os.getpid() and info.get(
                    "driver_generation") == 1:
                break
            time.sleep(0.05)
        assert info.get("driver_generation") == 1, info
        # both survivors re-attach: fresh beats land on the new driver
        deadline = time.time() + 15
        while time.time() < deadline:
            with d2._tt_lock:
                attached = {tid for tid in ("worker:0", "worker:1")
                            if tid in d2._first_beat}
            if len(attached) == 2:
                break
            time.sleep(0.05)
        assert len(attached) == 2, f"heartbeats never re-attached: {attached}"
        # live /metrics carries the recovery counters
        port = d2.metrics_port
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "driver_recoveries_total 1" in text
        assert "driver_tasks_readopted_total 2" in text
        assert "driver_task_restarts_total 0" in text
    finally:
        release.set()
        t2.join(timeout=30)
    assert not t2.is_alive(), "recovered driver did not finish"
    assert d2.session.status == JobStatus.SUCCEEDED, (
        d2.session.failure_message)
    assert relaunches == [], "recovery relaunched a live worker"
    assert d2._restarts == {}, "recovery charged the restart budget"

    inter = Path(tmp_job_dirs["history"]) / "intermediate" / "recover_test"
    recs = _last_trace_per_id(inter / TASK_TRACE_FILE)
    for tid in ("worker:0", "worker:1"):
        names = [n for n, *_ in recs[tid]["spans"]]
        assert names[0] == "readopted", names
        assert "first_heartbeat" in names, names
        assert names[-1] == "finished", names
        assert "restarted" not in names, names
        assert recs[tid]["attrs"]["driver_generation"] == 1

    # the journal was compacted at recovery and re-stamped: one meta
    # with the new endpoint, a recovered record, no duplicate launches
    state = load_state(job_dir / c.DRIVER_JOURNAL_FILE)
    assert state.recoveries >= 1
    assert state.tasks["worker:0"].terminal
    assert state.tasks["worker:1"].terminal


def test_recover_relaunches_dead_orphan_under_journaled_budget(
        tmp_job_dirs, tmp_path):
    """A worker whose journaled pid is provably DEAD at recovery is not
    re-adopted: its liveness clock comes back pre-expired, the first
    monitor ticks route it through the NORMAL budgeted-restart path,
    and the relaunch carries the next attempt ordinal. The journaled
    budget is respected: restarts already spent stay spent."""
    release = threading.Event()
    job_dir = tmp_path / "job"
    attempts_seen = []

    def stub(spec, index, env, handle, attempt):
        env_attempt = int(env[c.ENV_TASK_ATTEMPT])
        attempts_seen.append(env_attempt)
        if env_attempt == 1:
            return None         # first attempt: registers elsewhere below
        # the relaunched attempt (the DRIVER's ordinal, not the fresh
        # provisioner's) finishes the job; unlike a re-adopted handle it
        # has a live container watcher, so return a real exit code
        real = _resolving_stub(job_dir, release)
        real(spec, index, env, handle, attempt)
        return 0

    d1 = _make_driver(
        tmp_job_dirs, job_dir, stub,
        **{"tony.worker.instances": 1, "tony.worker.command": "stub",
           "tony.worker.max-restarts": 2,
           "tony.task.heartbeat-interval-ms": 100,
           "tony.task.max-missed-heartbeats": 3})
    # attempt 1 registers via a short-lived client, then 'dies': give the
    # journal a registered task whose pid is a real dead process
    dead = subprocess.Popen([sys.executable, "-c", "pass"])
    dead.wait()
    t1 = threading.Thread(target=d1.run, daemon=True)
    t1.start()
    deadline = time.time() + 10
    while not attempts_seen and time.time() < deadline:
        time.sleep(0.05)
    env1 = None
    deadline = time.time() + 10
    while env1 is None and time.time() < deadline:
        try:
            info = json.loads((job_dir / c.DRIVER_INFO_FILE).read_text())
            env1 = info
        except (OSError, ValueError):
            time.sleep(0.05)
    rpc = RpcClient(env1["host"], env1["port"],
                    token=d1.executor_token, role="executor")
    rpc.call("register_worker", task_id="worker:0", host="127.0.0.1",
             port=24000, attempt=1)
    rpc.close()
    _abrupt_death(d1, t1)
    # rewrite the journaled pid to the provably-dead one (the scripted
    # provisioner has no real pids; a real driver journals the Popen pid)
    state = load_state(job_dir / c.DRIVER_JOURNAL_FILE)
    state.tasks["worker:0"].pid = dead.pid
    rewrite_journal(job_dir / c.DRIVER_JOURNAL_FILE, state)

    prov2 = ScriptedProvisioner(stub)
    d2 = Driver.recover(str(job_dir), provisioner=prov2)
    d2.client_signal.set()
    assert d2._readopted == 0, "a dead pid must not count as re-adopted"
    t2 = threading.Thread(target=d2.run, daemon=True)
    t2.start()
    try:
        deadline = time.time() + 20
        while not prov2.launches and time.time() < deadline:
            time.sleep(0.05)
        assert prov2.launches == ["worker:0"], "orphan was not relaunched"
    finally:
        release.set()
        t2.join(timeout=30)
    assert d2.session.status == JobStatus.SUCCEEDED, (
        d2.session.failure_message)
    assert d2._restarts.get("worker:0") == 1, "budget not charged"
    assert attempts_seen[-1] == 2, attempts_seen

    inter = Path(tmp_job_dirs["history"]) / "intermediate" / "recover_test"
    recs = _last_trace_per_id(inter / TASK_TRACE_FILE)
    names = [n for n, *_ in recs["worker:0"]["spans"]]
    assert "restarted" in names and names[-1] == "finished", names


def test_recover_launches_partially_launched_roles_missing_tasks(
        tmp_job_dirs, tmp_path):
    """The driver can die INSIDE _request_role: some of a role's tasks
    journaled-launched, the rest never requested. The recovered driver
    must launch the missing siblings itself — the role is marked
    scheduled (so the DAG won't re-request it wholesale), and a
    never-journaled task otherwise has no liveness entry, no
    registration timeout, and no request coming (review finding)."""
    release = threading.Event()
    release.set()           # stubs run to completion immediately
    job_dir = tmp_path / "job"
    job_dir.mkdir()
    conf = _conf(tmp_job_dirs,
                 **{"tony.worker.instances": 2,
                    "tony.worker.command": "stub",
                    "tony.worker.max-restarts": 1,
                    "tony.task.heartbeat-interval-ms": 100,
                    "tony.task.max-missed-heartbeats": 3})
    conf.write_final(job_dir)
    # hand-craft the dead driver's journal: worker:0 launched (pid
    # provably dead -> expiry relaunch) and registered; worker:1 NEVER
    # launched — the mid-_request_role death shape
    dead = subprocess.Popen([sys.executable, "-c", "pass"])
    dead.wait()
    j = DriverJournal(job_dir / c.DRIVER_JOURNAL_FILE)
    j.record("meta", app_id="recover_test", token="recover-secret",
             session_id=0, rpc_port=0, driver_generation=0)
    j.record("launch", task="worker:0", attempt=1, container_id="c0",
             pid=dead.pid, host="127.0.0.1", t=time.time())
    j.record("register", task="worker:0", host="127.0.0.1", port=25000)
    j.close()

    def stub(spec, index, env, handle, attempt):
        real = _resolving_stub(job_dir, release, ports_base=25100)
        real(spec, index, env, handle, attempt)
        return 0

    prov = ScriptedProvisioner(stub)
    d2 = Driver.recover(str(job_dir), provisioner=prov)
    d2.client_signal.set()
    t2 = threading.Thread(target=d2.run, daemon=True)
    t2.start()
    t2.join(timeout=30)
    assert not t2.is_alive(), "recovered driver never finished"
    assert d2.session.status == JobStatus.SUCCEEDED, (
        d2.session.failure_message)
    # worker:1 launched by the recovery gap-fill, worker:0 relaunched by
    # the expiry path under the journaled budget
    assert sorted(prov.launches) == ["worker:0", "worker:1"], prov.launches
    assert d2._attempts["worker:1"] == 1
    assert d2._attempts["worker:0"] == 2


# --------------------------------------------------------------------------
# Heartbeater: outage window semantics
# --------------------------------------------------------------------------

class _Notes:
    def __init__(self):
        self.notes = []

    def note(self, name, value):
        self.notes.append((name, value))


def test_heartbeater_outage_reattaches_without_missed_inflation():
    """Transport failures open the outage window: the endpoint resolver
    runs per failed beat, the client is re-pointed, and once the beat
    lands again the outage closes — with heartbeats_missed NEVER
    incremented (the satellite contract: an outage must not read as
    this worker going missing, nor trip stale-sample detectors on
    reconnect)."""
    from tony_tpu.executor import Heartbeater
    from tony_tpu.metrics import HEARTBEATS_MISSED

    class _Client:
        def __init__(self):
            self.addr = ("old", 1)
            self.calls = 0

        def call(self, method, **params):
            self.calls += 1
            if self.addr == ("old", 1):
                raise ConnectionError("driver gone")
            return True

        def set_address(self, host, port):
            self.addr = (host, port)

    client = _Client()
    resolved = []

    def resolver():
        resolved.append(1)
        # the 'recovered driver' publishes its endpoint on the 3rd look
        return ("new", 2) if len(resolved) >= 3 else ("old", 1)

    notes = _Notes()
    hb = Heartbeater(client, "worker:0", interval_s=0.01,
                     max_failures=3, monitor=notes,
                     outage_grace_s=10.0, endpoint_resolver=resolver,
                     on_outage=lambda: pytest.fail("grace must not expire"))
    hb.start()
    deadline = time.time() + 5
    while client.addr == ("old", 1) and time.time() < deadline:
        time.sleep(0.01)
    # wait for a successful beat on the new endpoint (outage closes)
    deadline = time.time() + 5
    while hb.in_outage and time.time() < deadline:
        time.sleep(0.01)
    hb.stop_event.set()
    hb.join(timeout=5)
    assert client.addr == ("new", 2)
    assert not hb.in_outage and hb.outage_beats >= 3
    assert hb.missed == 0, "outage beats must not count as missed"
    assert not [v for n, v in notes.notes if n == HEARTBEATS_MISSED]


def test_heartbeater_outage_grace_exhaustion_fires_drain():
    """A driver that never comes back: on_outage fires once the grace
    runs dry (the executor checkpoint-drains), on_driver_lost does not,
    and missed stays 0."""
    from tony_tpu.executor import Heartbeater

    class _DeadClient:
        def call(self, method, **params):
            raise ConnectionError("refused")

        def set_address(self, host, port):
            pass

    drained = threading.Event()
    hb = Heartbeater(
        _DeadClient(), "worker:0", interval_s=0.01, max_failures=3,
        on_driver_lost=lambda: pytest.fail(
            "transport outage must not route to on_driver_lost"),
        outage_grace_s=0.15, endpoint_resolver=lambda: None,
        on_outage=drained.set)
    hb.start()
    assert drained.wait(5), "outage drain never fired"
    hb.join(timeout=5)
    assert not hb.is_alive()
    assert hb.missed == 0


def test_heartbeater_refusal_closes_the_outage_window():
    """An in-contact refusal (RpcError) proves transport is BACK: it
    must close an open outage window, or a lossy control plane
    (alternating refused/transport-failed beats) would let one later
    transport blip 'exhaust' a long-stale grace clock instantly and
    drain a worker the driver can see (review finding)."""
    from tony_tpu.executor import Heartbeater
    from tony_tpu.rpc import RpcError

    seq = {"n": 0}

    class _FlappingClient:
        def call(self, method, **params):
            seq["n"] += 1
            if seq["n"] % 2:
                raise ConnectionError("transport blip")
            raise RpcError("refused")       # the driver ANSWERED

        def set_address(self, host, port):
            pass

    hb = Heartbeater(
        _FlappingClient(), "worker:0", interval_s=0.02,
        max_failures=10_000,
        outage_grace_s=0.2, endpoint_resolver=lambda: None,
        on_outage=lambda: pytest.fail(
            "alternating refusal/transport beats must never exhaust "
            "the outage grace — each refusal resets the clock"))
    hb.start()
    time.sleep(1.0)     # ~50 beats: many grace windows' worth
    alive = hb.is_alive()
    hb.stop_event.set()
    hb.join(timeout=5)
    assert alive, "heartbeater died despite the driver answering"
    assert hb.missed >= 5       # the refusals still count as missed
    assert hb.outage_beats >= 5  # ... and the blips rode the window
    # (no assertion on the FINAL in_outage: it legitimately reflects
    # whichever half of the flap the last beat landed on)


# --------------------------------------------------------------------------
# router: discovery outage keeps the last-known fleet + stale gauge
# --------------------------------------------------------------------------

def _stub_replica_http():
    """A minimal live 'replica': answers /healthz 200 and /stats {}."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class H(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            body = b"{}" if self.path.startswith(
                ("/stats", "/progress")) else b'{"status": "ok"}'
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def test_router_discovery_outage_keeps_fleet_and_sets_stale_gauge():
    """Driver death mid-serving: discovery RAISES (RPC refused) — the
    router keeps serving its last-known fleet, router_discovery_stale
    reads 1 on /metrics and stats, and a recovered driver's working
    discovery clears it."""
    from tony_tpu.router import FleetRouter

    srv = _stub_replica_http()
    port = srv.server_address[1]
    calls = {"mode": "ok"}

    def discover():
        if calls["mode"] == "dead":
            raise ConnectionRefusedError("driver.json points at a corpse")
        return [("replica:0", "127.0.0.1", port)]

    router = FleetRouter([], prefill_chunk=4, seed=0, discover=discover)
    try:
        router.health_tick()
        assert list(router.stats()["replicas"]) == ["replica:0"]
        assert router.stats()["discovery_stale"] is False
        assert "router_discovery_stale 0" in router.prometheus_metrics()

        calls["mode"] = "dead"              # the driver is SIGKILLed
        for _ in range(3):
            router.health_tick()
        st = router.stats()
        assert list(st["replicas"]) == ["replica:0"], (
            "outage dropped the fleet")
        assert st["replicas"]["replica:0"]["up"] is True
        assert st["discovery_stale"] is True
        assert "router_discovery_stale 1" in router.prometheus_metrics()

        calls["mode"] = "ok"                # recovered driver answers
        router.health_tick()
        assert router.stats()["discovery_stale"] is False
        assert "router_discovery_stale 0" in router.prometheus_metrics()
    finally:
        router.shutdown()
        srv.shutdown()
        srv.server_close()


# --------------------------------------------------------------------------
# subprocess e2e: real SIGKILL, real executors, --recover entrypoint
# --------------------------------------------------------------------------

@pytest.mark.slow
def test_driver_sigkill_recover_e2e(tmp_path):
    """The full control-plane death cycle with REAL processes: a
    2-worker local job, the driver process SIGKILLed mid-job (executors
    orphaned but alive, riding the outage grace), `python -m
    tony_tpu.driver --recover` replays the journal in a fresh process,
    both workers re-adopt, and the job SUCCEEDS with zero task
    restarts."""
    from tony_tpu.client import TonyClient

    root = tmp_path
    steps_file = root / "steps"
    # a worker that takes ~8s: long enough to span kill + recovery
    cmd = (f"{sys.executable} -c \""
           "import time\n"
           "for i in range(80): time.sleep(0.1)\n"
           "\"")
    conf = TonyConf({
        "tony.staging.dir": str(root / "staging"),
        "tony.history.location": str(root / "history"),
        "tony.history.intermediate": str(root / "history/intermediate"),
        "tony.history.finished": str(root / "history/finished"),
        "tony.am.monitor-interval-ms": 100,
        "tony.task.registration-poll-interval-ms": 100,
        "tony.task.heartbeat-interval-ms": 200,
        "tony.task.driver-outage-grace-ms": 30000,
        "tony.worker.instances": 2,
        "tony.worker.command": cmd,
        "tony.worker.max-restarts": 1,
    })
    client = TonyClient(conf, poll_interval_s=0.2)
    client.submit()
    job_dir = Path(client.job_dir)
    # wait until both workers are registered (journal has the state)
    deadline = time.time() + 60
    registered = False
    while time.time() < deadline and not registered:
        try:
            state = load_state(job_dir / c.DRIVER_JOURNAL_FILE)
            registered = (state is not None and sum(
                1 for t in state.tasks.values() if t.registered) == 2)
        except Exception:
            pass
        time.sleep(0.2)
    assert registered, "workers never registered"
    driver_pid = client._driver_proc.pid
    os.kill(driver_pid, signal.SIGKILL)
    client._driver_proc.wait(timeout=10)
    time.sleep(1.0)     # let the executors notice and enter the outage

    env = {**os.environ}
    env["PYTHONPATH"] = str(Path(__file__).resolve().parent.parent) + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    rec_log = open(job_dir / "driver.log", "ab")
    rec = subprocess.Popen(
        [sys.executable, "-S", "-m", "tony_tpu.driver",
         "--job-dir", str(job_dir), "--recover"],
        env=env, stdout=rec_log, stderr=subprocess.STDOUT,
        start_new_session=True)
    try:
        # the recovered driver advertises a bumped generation; poll its
        # state to terminal through the rewritten driver.json
        from tony_tpu.rpc.protocol import derive_role_key

        deadline = time.time() + 60
        final = None
        while time.time() < deadline and final is None:
            try:
                info = json.loads(
                    (job_dir / c.DRIVER_INFO_FILE).read_text())
                if info.get("pid") != rec.pid:
                    time.sleep(0.2)
                    continue
                rpc = RpcClient(
                    info["host"], info["port"],
                    token=derive_role_key(client.token, "client"),
                    role="client", max_retries=2)
                state = rpc.call("get_application_state")
                if state["status"] in ("SUCCEEDED", "FAILED", "KILLED"):
                    final = state
                    rpc.call("finish_application")
                rpc.close()
            except Exception:
                pass
            time.sleep(0.3)
        assert final is not None, "recovered driver never went terminal"
        assert final["status"] == "SUCCEEDED", final
        rec.wait(timeout=30)
    finally:
        if rec.poll() is None:
            os.killpg(rec.pid, signal.SIGKILL)
        rec_log.close()

    inter = (root / "history/intermediate" / client.app_id)
    recs = _last_trace_per_id(inter / TASK_TRACE_FILE)
    for tid in ("worker:0", "worker:1"):
        names = [n for n, *_ in recs[tid]["spans"]]
        assert names[0] == "readopted", names
        assert names[-1] == "finished", names
        assert "restarted" not in names, (
            f"{tid} restarted across the outage: {names}")
