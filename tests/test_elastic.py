"""Elastic, preemption-tolerant training (docs/training-robustness.md).

The contract under test, driver to training step: a preemption notice
relayed over the heartbeat command channel drains the task (checkpoint at
the step boundary, exit EXIT_PREEMPTED) and relaunches it BUDGET-FREE
(trace mark ``preempted``); a worker lost beyond its restart budget
detaches from the gang instead of failing the job — survivors drain and
re-register into a new gang generation at the smaller world size (trace
mark ``resized``), and the slot rejoins when capacity returns; a straggler
whose step p50 lags the gang median beyond the configured factor gets a
budget-charged restart; and the killed container's completion can never
double-spend against any of those paths. Scripted-provisioner stubs speak
the real framed-JSON RPC (the test_task_trace pattern) so each scenario
runs in ~a second; one TINY e2e runs the real stack.
"""

import json
import os
import signal
import threading
import time
from pathlib import Path

import pytest

import tony_tpu.constants as c
from tony_tpu.api import JobStatus
from tony_tpu.cluster.provisioner import ContainerHandle, Provisioner
from tony_tpu.conf import TonyConf
from tony_tpu.driver import Driver
from tony_tpu.events.trace import TASK_TRACE_FILE, read_traces
from tony_tpu.rpc import RpcClient
from tony_tpu.rpc.protocol import RpcError, derive_role_key


def _conf(dirs, **extra):
    return TonyConf({
        "tony.staging.dir": dirs["staging"],
        "tony.history.location": dirs["history"],
        "tony.history.intermediate": dirs["history"] + "/intermediate",
        "tony.history.finished": dirs["history"] + "/finished",
        "tony.am.monitor-interval-ms": 50,
        "tony.task.registration-poll-interval-ms": 50,
        **extra,
    })


def _span_names(rec):
    return [n for n, _ in rec["spans"]]


class ScriptedProvisioner(Provisioner):
    """launch() runs ``script(spec, index, env, handle, attempt)`` on a
    thread; ``attempt`` counts launches per task so restart scripts can
    branch. stop_container() sets ``handle.extra["stop"]`` (an Event) so
    a script can model a draining child instead of ignoring the stop."""

    def __init__(self, script):
        super().__init__()
        self._script = script
        self._attempts: dict[str, int] = {}
        self._lock = threading.Lock()
        self.launches: list[str] = []
        self.stops: list[str] = []

    def launch(self, spec, index, env, log_dir):
        task_id = f"{spec.name}:{index}"
        with self._lock:
            attempt = self._attempts.get(task_id, 0)
            self._attempts[task_id] = attempt + 1
            self.launches.append(task_id)
        handle = ContainerHandle(
            container_id=f"stub_{task_id}_{attempt}",
            host="127.0.0.1", role=spec.name, index=index,
        )
        handle.extra["stop"] = threading.Event()
        threading.Thread(
            target=self._run, args=(spec, index, env, handle, attempt),
            daemon=True,
        ).start()
        return handle

    def _run(self, spec, index, env, handle, attempt):
        try:
            code = self._script(spec, index, env, handle, attempt)
        except Exception as e:                  # pragma: no cover - debug aid
            print(f"stub executor failed: {type(e).__name__}: {e}",
                  flush=True)
            code = 1
        if code is not None and self.on_completion:
            self.on_completion(handle, code)

    def stop_container(self, handle):
        with self._lock:
            self.stops.append(handle.container_id)
        handle.extra["stop"].set()

    def stop_all(self):
        pass


def _driver(dirs, tmp_path, script, name="elastic_test", **conf_extra):
    conf = _conf(dirs, **conf_extra)
    job_dir = tmp_path / f"job_{name}"
    job_dir.mkdir(exist_ok=True)
    conf.write_final(job_dir)
    driver = Driver(conf, app_id=name, job_dir=str(job_dir),
                    token="elastic-secret",
                    provisioner=ScriptedProvisioner(script))
    driver.client_signal.set()      # no client: don't wait for the ack
    return driver


def _rpc_for(env):
    return RpcClient(env[c.ENV_DRIVER_HOST], int(env[c.ENV_DRIVER_PORT]),
                     token=env.get(c.ENV_TOKEN, ""), role="executor")


def _client_rpc(driver):
    return RpcClient("127.0.0.1", driver.rpc_server.port,
                     token=derive_role_key("elastic-secret", "client"),
                     role="client")


def _trace_records(dirs, app_id):
    inter = Path(dirs["history"]) / "intermediate" / app_id
    return read_traces(inter / TASK_TRACE_FILE)


def _register_and_barrier(rpc, task_id, port):
    payload = rpc.call("register_worker", task_id=task_id,
                       host="127.0.0.1", port=port)
    while payload is None:
        rpc.call("heartbeat", task_id=task_id)
        time.sleep(0.03)
        payload = rpc.call("get_cluster_spec", task_id=task_id)
    return payload


# --------------------------------------------------------------------------
# preemption drain: heartbeat command -> drained exit -> budget-free relaunch
# --------------------------------------------------------------------------

def test_preempt_drain_budget_free(tmp_job_dirs, tmp_path):
    """The client relays a preemption for worker:0; the notice rides the
    heartbeat response exactly once, the 'drained' stub exits
    EXIT_PREEMPTED, and the relaunch spends NO restart budget. The trace
    carries preempting -> preempted and a fresh attempt chain; an
    executor key may not call preempt_task (ACL)."""
    registered = threading.Event()
    got: dict = {}

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        _register_and_barrier(rpc, task_id, 24000 + index)
        if attempt == 0:
            try:    # executor key must not be able to drain peers
                rpc.call("preempt_task", task_id=task_id)
                got["acl"] = "allowed"
            except RpcError as e:
                got["acl"] = str(e)
            registered.set()
            deadline = time.time() + 20
            while time.time() < deadline:
                res = rpc.call("heartbeat", task_id=task_id)
                if isinstance(res, dict) and res.get("preempt"):
                    got["cmd"] = res["preempt"]
                    break
                time.sleep(0.03)
            got["again"] = rpc.call("heartbeat", task_id=task_id)
            rpc.call("register_execution_result", task_id=task_id,
                     exit_code=c.EXIT_PREEMPTED)
            rpc.close()
            return c.EXIT_PREEMPTED     # drained at a step boundary
        rpc.call("register_execution_result", task_id=task_id, exit_code=0)
        rpc.close()
        return 0

    driver = _driver(tmp_job_dirs, tmp_path, script, name="preempt",
                     **{"tony.worker.instances": 1,
                        "tony.worker.command": "stub",
                        "tony.worker.max-restarts": 1,
                        "tony.task.heartbeat-interval-ms": 100})
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    try:
        assert registered.wait(20), "worker never registered"
        cl = _client_rpc(driver)
        try:
            assert cl.call("preempt_task", task_id="worker:9") is False
            # a registration can race the driver's launch bookkeeping by
            # a few ms; the RPC contract is retry-friendly (False = not
            # preemptible *yet*)
            deadline = time.time() + 5
            ok = cl.call("preempt_task", task_id="worker:0")
            while ok is not True and time.time() < deadline:
                time.sleep(0.05)
                ok = cl.call("preempt_task", task_id="worker:0")
            assert ok is True
        finally:
            cl.close()
    finally:
        registered.set()
    t.join(timeout=30)
    assert not t.is_alive(), "driver did not finish"
    assert driver.session.status == JobStatus.SUCCEEDED, (
        driver.session.failure_message)

    assert "authorization" in got["acl"], got["acl"]
    assert got["cmd"]["grace_ms"] == 3000       # conf default rides the wire
    assert got["again"] is True, "the preempt command is one-shot"
    assert driver.provisioner.launches == ["worker:0"] * 2
    text = driver.render_metrics()
    assert "driver_preemptions_total 1" in text
    assert "driver_task_restarts_total 0" in text
    recs = _trace_records(tmp_job_dirs, "preempt")
    assert len(recs) == 1
    names = _span_names(recs[0])
    assert "preempting" in names and "preempted" in names
    assert names.count("requested") == 2, names
    assert names[-1] == "finished"
    assert recs[0]["attrs"]["restarts"] == 0


def test_self_reported_preemption_and_uncommanded_drain(tmp_job_dirs,
                                                        tmp_path):
    """Both executor-initiated flavors are budget-free: worker:0 calls
    notify_preemption (the SIGTERM relay path) and dies 137; worker:1
    just exits EXIT_PREEMPTED (its child saw the notice first). Each
    relaunch is budget-free and the job succeeds."""

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        _register_and_barrier(rpc, task_id, 24100 + index)
        if attempt == 0:
            if index == 0:
                rpc.call("notify_preemption", task_id=task_id)
                rpc.close()
                return c.EXIT_KILLED    # host reclaimed mid-drain
            rpc.close()
            return c.EXIT_PREEMPTED     # drained without driver notice
        rpc.call("register_execution_result", task_id=task_id, exit_code=0)
        rpc.close()
        return 0

    driver = _driver(tmp_job_dirs, tmp_path, script, name="selfpreempt",
                     **{"tony.worker.instances": 2,
                        "tony.worker.command": "stub",
                        "tony.task.heartbeat-interval-ms": 100})
    status = driver.run()
    assert status == JobStatus.SUCCEEDED, driver.session.failure_message
    assert sorted(driver.provisioner.launches) == ["worker:0"] * 2 + [
        "worker:1"] * 2
    text = driver.render_metrics()
    assert "driver_preemptions_total 2" in text
    assert "driver_task_restarts_total 0" in text
    for rec in _trace_records(tmp_job_dirs, "selfpreempt"):
        names = _span_names(rec)
        assert "preempted" in names, names
        assert names[-1] == "finished"
        assert rec["attrs"]["restarts"] == 0


# --------------------------------------------------------------------------
# budget-accounting guard: preempt relaunch vs racing completion/expiry
# --------------------------------------------------------------------------

def test_preempt_expiry_race_single_spend(tmp_job_dirs, tmp_path):
    """The killed container's completion races heartbeat expiry (the
    delayed-completion fault hook): the preempted task goes silent, its
    completion is held 700ms, and expiry fires first. Exactly ONE
    relaunch happens and at most one budget unit is spent — the delayed
    completion reads as superseded and must not relaunch or spend
    again (the PR 7 guard extended to the preempt path)."""
    preempt_seen = threading.Event()
    registered = threading.Event()

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        _register_and_barrier(rpc, task_id, 24200 + index)
        if attempt == 0:
            registered.set()
            deadline = time.time() + 20
            while time.time() < deadline:
                res = rpc.call("heartbeat", task_id=task_id)
                if isinstance(res, dict) and res.get("preempt"):
                    preempt_seen.set()
                    break
                time.sleep(0.03)
            rpc.close()
            # goes SILENT (no more beats); the drained exit's completion
            # is delayed by TONY_TEST_COMPLETION_NOTIFICATION_DELAY_MS,
            # so heartbeat expiry (0.3s) wins the race
            return c.EXIT_PREEMPTED
        rpc.call("register_execution_result", task_id=task_id, exit_code=0)
        rpc.close()
        return 0

    os.environ[c.TEST_COMPLETION_DELAY_MS] = "700"
    try:
        driver = _driver(tmp_job_dirs, tmp_path, script, name="preemptrace",
                         **{"tony.worker.instances": 1,
                            "tony.worker.command": "stub",
                            "tony.worker.max-restarts": 2,
                            "tony.task.heartbeat-interval-ms": 100,
                            "tony.task.max-missed-heartbeats": 3})
        t = threading.Thread(target=driver.run, daemon=True)
        t.start()
        try:
            assert registered.wait(20)
            cl = _client_rpc(driver)
            try:
                deadline = time.time() + 5
                ok = cl.call("preempt_task", task_id="worker:0")
                while ok is not True and time.time() < deadline:
                    time.sleep(0.05)
                    ok = cl.call("preempt_task", task_id="worker:0")
                assert ok is True
            finally:
                cl.close()
            assert preempt_seen.wait(20), "notice never delivered"
        finally:
            registered.set()
        t.join(timeout=30)
    finally:
        del os.environ[c.TEST_COMPLETION_DELAY_MS]
    assert not t.is_alive(), "driver did not finish"
    assert driver.session.status == JobStatus.SUCCEEDED, (
        driver.session.failure_message)
    # the core guarantee: one replacement, never two, and the budget was
    # charged at most once (whichever path won the race)
    assert driver.provisioner.launches == ["worker:0"] * 2
    recs = _trace_records(tmp_job_dirs, "preemptrace")
    assert len(recs) == 1
    assert recs[0]["attrs"]["restarts"] <= 1
    names = _span_names(recs[0])
    assert names[-1] == "finished"
    assert names.count("requested") == 2, names


# --------------------------------------------------------------------------
# elastic gang resize: down on loss past budget, up when capacity returns
# --------------------------------------------------------------------------

def test_resize_down_then_up(tmp_job_dirs, tmp_path):
    """worker:1 crashes with NO restart budget: instead of failing the
    job the driver detaches it, drains worker:0, and re-forms the gang
    at world size 1 (generation 1). When the rescale timer fires the
    slot rejoins: another drain, generation 2, world size 2, and the
    whole job finishes clean — two resizes, zero budget units."""
    release = threading.Event()
    payloads: dict = {}

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        payload = _register_and_barrier(rpc, task_id, 24300 + index)
        payloads[(index, attempt)] = payload
        if index == 1 and attempt == 0:
            # crash only after the survivor cleared the barrier — a stub
            # thread stuck polling get_cluster_spec can't be SIGTERMed
            # out of the poll the way a real executor process would be
            deadline = time.time() + 10
            while (0, 0) not in payloads and time.time() < deadline:
                time.sleep(0.02)
            rpc.close()
            return 1        # crash; budget 0 -> resize, not job failure
        stop = handle.extra["stop"]
        deadline = time.time() + 30
        while time.time() < deadline:
            if stop.is_set():           # resize drain: checkpoint + exit
                rpc.close()
                return c.EXIT_PREEMPTED
            if release.is_set():
                rpc.call("register_execution_result", task_id=task_id,
                         exit_code=0)
                rpc.close()
                return 0
            try:
                rpc.call("heartbeat", task_id=task_id)
            except Exception:
                pass
            time.sleep(0.05)
        rpc.close()
        return 1

    driver = _driver(tmp_job_dirs, tmp_path, script, name="resize",
                     **{"tony.worker.instances": 2,
                        "tony.worker.command": "stub",
                        "tony.worker.max-restarts": 0,
                        "tony.train.elastic-enabled": True,
                        "tony.train.elastic-min-instances": 1,
                        "tony.train.rescale-retry-ms": 500,
                        "tony.task.heartbeat-interval-ms": 100})
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    try:
        # wait until the gang is re-formed at FULL size in generation 2:
        # worker:0's third attempt and worker:1's second saw the barrier
        deadline = time.time() + 25
        while time.time() < deadline:
            if (1, 1) in payloads and (0, 2) in payloads:
                break
            time.sleep(0.05)
        assert (0, 1) in payloads, f"resize-down relaunch missing: {payloads}"
        assert (1, 1) in payloads and (0, 2) in payloads, (
            f"rescale-up never completed: {sorted(payloads)}")
    finally:
        release.set()
    t.join(timeout=30)
    assert not t.is_alive(), "driver did not finish"
    assert driver.session.status == JobStatus.SUCCEEDED, (
        driver.session.failure_message)

    # formation history: full gang (gen 0, world 2) -> survivors-only
    # (gen 1, world 1) -> restored (gen 2, world 2)
    assert len(payloads[(0, 0)]["cluster"]["worker"]) == 2
    assert payloads[(0, 0)]["gang_generation"] == 0
    assert len(payloads[(0, 1)]["cluster"]["worker"]) == 1
    assert payloads[(0, 1)]["gang_generation"] == 1
    assert len(payloads[(0, 2)]["cluster"]["worker"]) == 2
    assert payloads[(0, 2)]["gang_generation"] == 2
    assert payloads[(1, 1)]["gang_generation"] == 2

    text = driver.render_metrics()
    assert "driver_gang_resizes_total 2" in text
    assert "driver_task_restarts_total 0" in text
    assert 'driver_tasks{state="detached"} 0' in text
    recs = {r["id"]: r for r in _trace_records(tmp_job_dirs, "resize")}
    assert set(recs) == {"worker:0", "worker:1"}
    for rec in recs.values():
        names = _span_names(rec)
        assert "resized" in names, names
        assert names[-1] == "finished"
        assert rec["attrs"]["restarts"] == 0
    # worker:0 was drained twice (down + up): three attempts in one trace
    assert _span_names(recs["worker:0"]).count("requested") == 3


def test_resize_down_stays_down_without_capacity(tmp_job_dirs, tmp_path):
    """With a rescale timer that never fires inside the test window, the
    job finishes at the SMALLER world size: the detached task is not
    tracked, the survivor's success completes the job, and the detached
    trace seals 'killed' at stop."""
    barrier_cleared = threading.Event()

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        payload = _register_and_barrier(rpc, task_id, 24400 + index)
        if index == 0 and attempt == 0:
            barrier_cleared.set()
        if index == 1:
            barrier_cleared.wait(10)    # see test_resize_down_then_up
            rpc.close()
            return 1                    # lost for good
        if attempt == 0:                # survivor: beat until drained (a
            deadline = time.time() + 20  # registered stub that stops
            while (time.time() < deadline   # beating would expire)
                   and not handle.extra["stop"].is_set()):
                try:
                    rpc.call("heartbeat", task_id=task_id)
                except Exception:
                    pass
                time.sleep(0.05)
            rpc.close()
            return c.EXIT_PREEMPTED
        assert len(payload["cluster"]["worker"]) == 1
        rpc.call("register_execution_result", task_id=task_id, exit_code=0)
        rpc.close()
        return 0

    driver = _driver(tmp_job_dirs, tmp_path, script, name="resizedown",
                     **{"tony.worker.instances": 2,
                        "tony.worker.command": "stub",
                        "tony.worker.max-restarts": 0,
                        "tony.train.elastic-enabled": True,
                        "tony.train.rescale-retry-ms": 600000,
                        "tony.task.heartbeat-interval-ms": 100})
    status = driver.run()
    assert status == JobStatus.SUCCEEDED, driver.session.failure_message
    assert driver.provisioner.launches.count("worker:1") == 1, (
        "no capacity returned: the lost slot must not relaunch")
    recs = {r["id"]: r for r in _trace_records(tmp_job_dirs, "resizedown")}
    assert _span_names(recs["worker:0"])[-1] == "finished"
    assert "resized" in _span_names(recs["worker:1"])
    assert _span_names(recs["worker:1"])[-1] == "killed"
    text = driver.render_metrics()
    assert "driver_gang_resizes_total 1" in text


def test_chief_loss_is_still_fatal(tmp_job_dirs, tmp_path):
    """Elasticity must not mask a chief death: worker:0 (the chief when
    no chief role exists) crashing past its budget fails the job."""
    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        _register_and_barrier(rpc, task_id, 24500 + index)
        if index == 0:
            rpc.close()
            return 1
        deadline = time.time() + 20
        while (time.time() < deadline
               and not handle.extra["stop"].is_set()):
            try:
                rpc.call("heartbeat", task_id=task_id)
            except Exception:
                pass
            time.sleep(0.05)
        rpc.close()
        return c.EXIT_KILLED

    driver = _driver(tmp_job_dirs, tmp_path, script, name="chiefloss",
                     **{"tony.worker.instances": 2,
                        "tony.worker.command": "stub",
                        "tony.worker.max-restarts": 0,
                        "tony.train.elastic-enabled": True,
                        "tony.application.fail-on-worker-failure-enabled":
                            True,
                        "tony.task.heartbeat-interval-ms": 100})
    status = driver.run()
    assert status == JobStatus.FAILED
    assert "worker:0" in driver.session.failure_message


# --------------------------------------------------------------------------
# straggler action: pushed step p50 lagging the role median -> restart
# --------------------------------------------------------------------------

def test_straggler_restart_budget_charged(tmp_job_dirs, tmp_path):
    """Three workers push step-time p50s; worker:2 reports 10x the
    median and is restarted through the normal budget with a
    'straggler' cause. Its replacement (fast) finishes with the rest."""
    release = threading.Event()

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        _register_and_barrier(rpc, task_id, 24600 + index)
        p50 = 1.0 if (index == 2 and attempt == 0) else 0.1
        rpc.call("update_metrics", task_id=task_id,
                 metrics=[{"name": "max_step_time_p50_s", "value": p50}])
        deadline = time.time() + 30
        while time.time() < deadline and not release.is_set():
            if handle.extra["stop"].is_set():
                rpc.close()
                return c.EXIT_KILLED    # stopped for the restart
            try:
                rpc.call("heartbeat", task_id=task_id)
            except Exception:
                pass
            time.sleep(0.05)
        rpc.call("register_execution_result", task_id=task_id, exit_code=0)
        rpc.close()
        return 0

    driver = _driver(tmp_job_dirs, tmp_path, script, name="straggler",
                     **{"tony.worker.instances": 3,
                        "tony.worker.command": "stub",
                        "tony.worker.max-restarts": 1,
                        "tony.train.straggler-restart-factor": 3,
                        "tony.train.straggler-grace-checks": 1,
                        "tony.task.heartbeat-interval-ms": 100})
    t = threading.Thread(target=driver.run, daemon=True)
    t.start()
    try:
        deadline = time.time() + 20
        while (time.time() < deadline
               and driver.provisioner.launches.count("worker:2") < 2):
            time.sleep(0.05)
        assert driver.provisioner.launches.count("worker:2") == 2, (
            "straggler was never restarted")
    finally:
        release.set()
    t.join(timeout=30)
    assert not t.is_alive(), "driver did not finish"
    assert driver.session.status == JobStatus.SUCCEEDED, (
        driver.session.failure_message)
    assert driver.provisioner.launches.count("worker:0") == 1
    assert driver.provisioner.launches.count("worker:1") == 1
    text = driver.render_metrics()
    assert "driver_task_restarts_total 1" in text
    recs = {r["id"]: r for r in _trace_records(tmp_job_dirs, "straggler")}
    names = _span_names(recs["worker:2"])
    assert names.count("restarted") == 1
    assert "straggler" in recs["worker:2"]["attrs"]["last_cause"]
    assert names[-1] == "finished"


# --------------------------------------------------------------------------
# chaos knobs: seeded heartbeat drop + step-triggered preemption
# --------------------------------------------------------------------------

def test_chaos_heartbeat_drop_knob(tmp_job_dirs, tmp_path, monkeypatch):
    """At drop rate 1.0 every heartbeat RPC errors (the executor counts a
    miss); the knob is read once at construction and seeded."""
    from tony_tpu.driver import DriverService

    monkeypatch.setenv(c.TEST_DRIVER_HEARTBEAT_DROP_RATE, "1.0")
    driver = _driver(tmp_job_dirs, tmp_path, lambda *a: 0, name="hbdrop",
                     **{"tony.worker.instances": 1,
                        "tony.worker.command": "stub"})
    svc = DriverService(driver)
    with pytest.raises(RuntimeError, match="chaos"):
        svc.heartbeat("worker:0")
    assert "worker:0" not in driver.heartbeats, "a dropped beat records nothing"
    driver.rpc_server.stop()
    if driver._metrics_httpd is not None:   # pragma: no cover
        driver._metrics_httpd.shutdown()


def test_chaos_preempt_at_step(tmp_job_dirs, tmp_path, monkeypatch):
    """TONY_TEST_DRIVER_PREEMPT_AT_STEP: once the gang's pushed
    train_step reaches the trigger, exactly one seeded preemption drain
    fires; the drained stub relaunches budget-free and finishes."""
    monkeypatch.setenv(c.TEST_DRIVER_PREEMPT_AT_STEP, "5")
    monkeypatch.setenv(c.TEST_DRIVER_CHAOS_SEED, "7")

    def script(spec, index, env, handle, attempt):
        rpc = _rpc_for(env)
        task_id = f"{spec.name}:{index}"
        _register_and_barrier(rpc, task_id, 24700 + index)
        if attempt == 0:
            rpc.call("update_metrics", task_id=task_id,
                     metrics=[{"name": "max_train_step", "value": 9}])
            deadline = time.time() + 20
            while time.time() < deadline:
                res = rpc.call("heartbeat", task_id=task_id)
                if isinstance(res, dict) and res.get("preempt"):
                    rpc.close()
                    return c.EXIT_PREEMPTED
                time.sleep(0.03)
            rpc.close()
            return 1
        rpc.call("register_execution_result", task_id=task_id, exit_code=0)
        rpc.close()
        return 0

    driver = _driver(tmp_job_dirs, tmp_path, script, name="chaospreempt",
                     **{"tony.worker.instances": 1,
                        "tony.worker.command": "stub",
                        "tony.task.heartbeat-interval-ms": 100})
    status = driver.run()
    assert status == JobStatus.SUCCEEDED, driver.session.failure_message
    assert driver.provisioner.launches == ["worker:0"] * 2
    assert driver._chaos_preempt_fired is True
    text = driver.render_metrics()
    assert "driver_preemptions_total 1" in text
    assert "driver_task_restarts_total 0" in text


# --------------------------------------------------------------------------
# executor/train units: flag files, StepTimer poll, overlapped checkpoints
# --------------------------------------------------------------------------

def test_write_preempt_flag_and_steptimer_poll(tmp_path):
    """The executor's drain relay meets the training child's poll: the
    tmp+renamed flag makes preempt_requested stick and is consumed."""
    from tony_tpu.executor import write_preempt_flag
    from tony_tpu.train.profiling import StepTimer

    assert write_preempt_flag(None, {"grace_ms": 10}) is None
    step_log = tmp_path / "w0.steps.jsonl"
    timer = StepTimer(step_log, window=2)
    timer.tick()
    assert timer.preempt_requested is False
    flag = write_preempt_flag(str(step_log), {"grace_ms": 1500})
    assert flag == str(step_log) + c.PREEMPT_REQUEST_SUFFIX
    req = json.loads(Path(flag).read_text())
    assert req["grace_ms"] == 1500.0
    # the poll is time-gated at ~0.25s; wait past the gate then tick
    time.sleep(0.3)
    timer.tick()
    assert timer.preempt_requested is True
    assert not Path(flag).exists(), "the notice is consumed"


def test_heartbeater_relays_preempt_command():
    """A dict heartbeat response carrying 'preempt' reaches on_preempt
    exactly once (and the profile callback stays untouched)."""
    from tony_tpu.executor import Heartbeater

    class _Client:
        def __init__(self):
            self.beats = 0

        def call(self, method, **params):
            self.beats += 1
            if self.beats == 1:
                return {"preempt": {"grace_ms": 700}}
            return True

    pre, prof = [], []
    client = _Client()
    hb = Heartbeater(client, "worker:0", interval_s=0.01,
                     on_command=prof.append, on_preempt=pre.append)
    hb.start()
    deadline = time.time() + 5
    while client.beats < 3 and time.time() < deadline:
        time.sleep(0.01)
    hb.stop_event.set()
    hb.join(timeout=5)
    assert pre == [{"grace_ms": 700}]
    assert prof == []


def test_checkpoint_manager_overlapped_save(tmp_path):
    """save_async returns immediately after the host snapshot, the
    background writer finalizes atomically (wait() drains), the newest
    step wins, and restore round-trips."""
    jax = pytest.importorskip("jax")
    import jax.numpy as jnp

    from tony_tpu.train.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpt"), save_interval=1)
    assert mgr.last_saved_step is None
    mgr.save_async(2, {"w": jnp.arange(4.0), "n": jnp.float32(1)})
    mgr.save_async(4, {"w": jnp.arange(4.0) * 3, "n": jnp.float32(9)})
    mgr.wait()
    assert mgr.last_saved_step == 4
    assert mgr.latest_step() == 4
    restored = mgr.restore(template={"w": jnp.zeros(4), "n": jnp.float32(0)})
    assert float(restored["n"]) == 9.0
    assert float(restored["w"][2]) == 6.0
    mgr.close()


def test_jax_ranks_follow_real_task_identity_after_resize():
    """A resized gang's cluster spec is COMPACTED; rank assignment must
    key off real task ids, not list positions — otherwise the survivor
    above the detached slot gets no rank entry and falls back to a
    process_id >= num_processes, and the re-formed gang can never
    initialize jax.distributed."""
    from tony_tpu.runtimes.jax_runtime import JaxDriverAdapter
    from tony_tpu.session import Session

    conf = TonyConf({"tony.worker.instances": 3,
                     "tony.worker.command": "stub"})
    s = Session(conf)
    for i in range(3):
        assert s.register_task(f"worker:{i}", "h", 100 + i) is not None
    adapter = JaxDriverAdapter()
    adapter.set_session(s)
    full = adapter.cluster_spec_payload("worker:0")
    assert full["ranks"] == {"worker:0": 0, "worker:1": 1, "worker:2": 2}

    s.detach_task("worker:1")           # lost past its budget
    s.begin_generation()
    assert s.register_task("worker:0", "h", 100) is not None
    assert s.register_task("worker:2", "h", 102) is not None
    payload = adapter.cluster_spec_payload("worker:0")
    assert payload["ranks"] == {"worker:0": 0, "worker:2": 1}, payload
    assert payload["num_processes"] == 2
    assert payload["coordinator_address"] == "h:100"
    assert payload["cluster"]["worker"] == ["h:100", "h:102"]
    assert payload["gang_generation"] == 1


def test_session_detach_semantics():
    """Session-level resize contract: detached slots leave the barrier
    predicate, the cluster spec, registration, and the tracked set; a
    generation bump forces full re-registration."""
    from tony_tpu.session import Session

    conf = TonyConf({"tony.worker.instances": 2,
                     "tony.worker.command": "stub"})
    s = Session(conf)
    assert s.register_task("worker:0", "h", 1) is not None
    assert s.register_task("worker:1", "h", 2) is not None
    assert s.all_registered()
    assert s.detach_task("worker:1")
    assert s.all_registered(), "detached slots are not gang-gated"
    assert s.cluster_spec() == {"worker": ["h:1"]}
    assert [t.task_id for t in s.tracked_tasks()] == ["worker:0"]
    assert s.register_task("worker:1", "h", 3) is None, (
        "a detached slot's zombie may not re-register")
    gen = s.begin_generation()
    assert gen == 1 and not s.all_registered()
    assert s.reattach_task("worker:1")
    assert s.register_task("worker:1", "h", 4) is not None
    assert not s.all_registered()       # worker:0 must re-register too
    assert s.register_task("worker:0", "h", 1) is not None
    assert s.all_registered()


# --------------------------------------------------------------------------
# TINY e2e: SIGKILL mid-train -> resize -> checkpoint resume, step-continuous
# --------------------------------------------------------------------------

def _step_sequence(step_log: Path) -> list[int]:
    steps = []
    for line in step_log.read_text().splitlines():
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if isinstance(rec.get("train_step"), int):
            steps.append(rec["train_step"])
    return steps


def _continuity(steps: list[int]) -> int:
    """Recomputed-step count over a multi-attempt StepTimer sequence;
    asserts there is never a silent skip."""
    recomputed = 0
    for prev, cur in zip(steps, steps[1:]):
        if cur <= prev:     # attempt boundary: resumed from a checkpoint
            recomputed += prev - cur + 1
        else:
            assert cur == prev + 1, (
                f"silent step skip: {prev} -> {cur} in {steps}")
    return recomputed


def test_e2e_sigkill_resize_checkpoint_resume(tmp_job_dirs, tmp_path):
    """The acceptance scenario end to end on the real stack: a 2-worker
    elastic job runs the elastic_train drill; worker:1's child SIGKILLs
    itself at step 12 on EVERY attempt with a 1-restart budget. Kill #1
    spends the budget and the relaunch REWINDS to the latest checkpoint
    (a real recompute, bounded by save_interval, asserted from the
    StepTimer JSONL); kill #2 exhausts the budget and the driver resizes
    the gang down instead of failing the job — the survivor drains on
    the SIGTERM (checkpoint at the step boundary), relaunches budget-
    free at world size 1, and finishes. Both the resize and the restart
    are visible in tasks.trace.jsonl; no log shows a silent step skip."""
    import sys

    from tony_tpu.client import TonyClient

    ckpt_root = tmp_path / "ckpts"
    ckpt_root.mkdir()
    save_interval = 5
    total_steps = 40
    cmd = (f"{sys.executable} -m tony_tpu.examples.elastic_train "
           f"--steps {total_steps} --save-interval {save_interval} "
           f"--ckpt-dir {ckpt_root}/w$TONY_TASK_INDEX")
    conf = _conf(
        tmp_job_dirs,
        **{"tony.worker.instances": 2,
           "tony.worker.command": cmd,
           "tony.worker.max-restarts": 1,
           "tony.train.elastic-enabled": True,
           "tony.train.elastic-min-instances": 1,
           "tony.train.rescale-retry-ms": 600000,   # stay resized down
           "tony.task.preempt-grace-ms": 4000,
           "tony.task.heartbeat-interval-ms": 250,
           "tony.task.metrics-interval-ms": 500,
           "tony.execution.env": " ".join([
               "ELASTIC_TRAIN_STEP_MS=60",
               "ELASTIC_TRAIN_KILL=1:12",     # fires on every attempt
               "JAX_PLATFORMS=cpu",
           ])})
    client = TonyClient(conf, poll_interval_s=0.2)
    client.submit()
    status = client.monitor()
    logs = "\n".join(
        f"==== {p} ====\n{p.read_text()[-2500:]}"
        for p in sorted(Path(client.job_dir).rglob("*.std*")))
    assert status == JobStatus.SUCCEEDED, logs

    # gang resize + the budgeted restart are visible in the task traces
    inter = Path(tmp_job_dirs["history"]) / "intermediate" / client.app_id
    recs = {r["id"]: r for r in read_traces(inter / TASK_TRACE_FILE)}
    w0, w1 = _span_names(recs["worker:0"]), _span_names(recs["worker:1"])
    assert "resized" in w0 and "resized" in w1, (w0, w1)
    assert w0[-1] == "finished"
    assert recs["worker:0"]["attrs"]["restarts"] == 0, (
        "the survivor's drain relaunch must be budget-free")
    assert w1.count("restarted") == 1, w1
    assert recs["worker:1"]["attrs"]["restarts"] == 1

    # step-counter continuity from the StepTimer JSONLs. worker:1's
    # budgeted restart is a REAL rewind: it resumed from the latest
    # checkpoint, recomputing at least one and at most save_interval
    # steps. worker:0's drain checkpointed at the exit boundary, so its
    # relaunch recomputes nothing. Neither log may skip a step.
    w1_steps = _step_sequence(
        Path(client.job_dir) / "logs" / "worker_1.steps.jsonl")
    assert w1_steps, "worker:1 left no step records"
    w1_recomputed = _continuity(w1_steps)
    assert 1 <= w1_recomputed <= save_interval, (w1_recomputed, w1_steps)

    w0_steps = _step_sequence(
        Path(client.job_dir) / "logs" / "worker_0.steps.jsonl")
    assert w0_steps, "worker:0 left no step records"
    assert _continuity(w0_steps) <= save_interval, w0_steps
    assert w0_steps[0] == 0 and w0_steps[-1] == total_steps - 1, w0_steps
