"""Framework runtime SPI.

Mirrors the reference's pluggable Framework interface (Framework.java:32-71),
split the same way into a driver-side adapter (cluster-spec construction, gang
gating, config validation, health, rendezvous callbacks) and an executor-side
adapter (env building, process exec). Discovery is by registry name keyed off
``tony.application.framework`` (reference uses java.util.ServiceLoader,
FrameworkRuntimeProvider.java:30-67).
"""

from __future__ import annotations

import logging
import subprocess
import os
import threading
import time
from typing import TYPE_CHECKING, Any

from ..api import DistributedMode

if TYPE_CHECKING:
    from ..conf import TonyConf
    from ..session import Session

log = logging.getLogger(__name__)


def spawn_or_adopt(ctx: "TaskContext",
                   contract_env: dict[str, str]) -> Any:
    """Start the user process for ``ctx``: adopt a pre-warmed standby
    from the host's warm pool (tony_tpu/warmpool.py) when one is ready
    and the command is a single python invocation, else cold-``Popen``
    through a shell. Adoption marks ``child_adopted`` on the task trace
    (pool hit); a configured-but-missed pool marks ``child_spawned``
    with a ``warm_pool: miss`` attr — the driver counts both into
    ``driver_warm_pool_{adoptions,misses}_total``. A successful
    adoption replenishes the pool in the background so the NEXT launch
    (relaunch, resize, roll) finds a warm standby too. Any adoption
    problem degrades to the cold path, never to a failed launch."""
    from ..warmpool import WarmPool

    pool = None
    try:
        pool = WarmPool.from_context(ctx)
    except Exception:
        log.exception("warm pool unavailable; spawning cold")
    if pool is not None:
        child = None
        try:
            child = pool.adopt(ctx.command,
                               {**os.environ, **contract_env},
                               cwd=ctx.work_dir)
        except Exception:
            log.exception("warm pool adoption failed; spawning cold")
        if child is not None:
            ctx.child_process = child
            ctx.note_span("child_adopted",
                          attrs={"warm_pool": "hit",
                                 "standby_warmed_s": child.warmed_s})

            def _replenish():
                # deferred: an immediate respawn's warmup would compete
                # with the adopted child's own first-step compile
                from ..warmpool import replenish_delay_s

                time.sleep(replenish_delay_s())
                try:
                    pool.ensure()
                except Exception:
                    log.exception("warm pool replenish failed")

            threading.Thread(target=_replenish, name="warmpool-replenish",
                             daemon=True).start()
            return child
    proc = subprocess.Popen(
        ["bash", "-c", ctx.command],
        env={**os.environ, **contract_env}, cwd=ctx.work_dir or None,
    )
    ctx.child_process = proc
    ctx.note_span("child_spawned",
                  attrs={"warm_pool": "miss"} if pool is not None else None)
    return proc


class DriverAdapter:
    """Driver-side behavior — reference Framework.ApplicationMasterAdapter."""

    def __init__(self) -> None:
        self.session: "Session | None" = None

    def set_session(self, session: "Session") -> None:
        self.session = session

    def validate_and_update_config(self, conf: "TonyConf") -> None:
        """Hook to inject roles / reject illegal keys before the session is
        built (reference HorovodRuntime.validateAndUpdateConfig:210-232)."""

    def can_start_task(self, mode: DistributedMode, task_id: str) -> bool:
        """The gang barrier: may `task_id` receive its cluster spec yet?
        (reference MLGenericRuntime.java:80-98)."""
        raise NotImplementedError

    def cluster_spec_payload(self, task_id: str) -> dict[str, Any]:
        """What register_worker/get_cluster_spec returns once the barrier
        opens. Base payload is the role->addresses map plus any named
        service ports tasks have published (publish_ports RPC — the
        generalization of the reference's TF_CONFIG endpoint plumbing);
        runtimes add their rendezvous data (reference
        constructClusterSpec)."""
        assert self.session is not None
        payload: dict[str, Any] = {"cluster": self.session.cluster_spec()}
        # which elastic gang formation this spec describes — bumped by
        # every resize, so an executor/tooling can tell a re-formed
        # (smaller or restored) gang from the one it first joined
        payload["gang_generation"] = self.session.gang_generation
        ports = self.session.service_ports()
        if ports:
            payload["service_ports"] = ports
        return payload

    def is_healthy(self, conf: "TonyConf") -> bool:
        """Periodic health check from the driver monitor loop (reference
        allocation-timeout deadlock breaker, MLGenericRuntime.java:110-147)."""
        return True

    def receive_callback_info(self, task_id: str, payload: dict[str, Any]) -> None:
        """Runtime rendezvous callbacks (reference receiveTaskCallbackInfo)."""


class TaskAdapter:
    """Executor-side behavior — reference Framework.TaskExecutorAdapter."""

    def need_tb_port(self) -> bool:
        return False

    def build_env(self, ctx: "TaskContext") -> dict[str, str]:
        """Map the cluster-spec payload into the env contract the user's
        training process expects."""
        raise NotImplementedError

    def run(self, ctx: "TaskContext") -> int:
        """Default: fork the user command through a shell with the built env,
        stream output, return its exit code (reference
        Utils.executeShell:299-328 — minus the hadoop-classpath preamble,
        which has no TPU equivalent) — or, when the warm pool has a ready
        standby (``tony.warmpool.size``), ADOPT it instead of cold-spawning
        (spawn_or_adopt; docs/performance.md "Launch path"). With
        `tony.docker.enabled` the command runs inside the configured image
        instead (reference Docker-on-YARN, HadoopCompatibleAdapter.java:
        45-159); container mode always spawns cold."""
        from .. import constants as c
        from ..utils import containers

        contract_env = {**ctx.base_child_env, **self.build_env(ctx)}
        if containers.container_enabled(ctx.conf):
            # execution-env / role-env vars reach bare tasks via os.environ
            # inheritance; containers need them forwarded explicitly
            contract_env = {
                **containers.passthrough_env(ctx.conf, ctx.job_name),
                **contract_env,
            }
            name = containers.container_name(
                ctx.base_child_env.get(c.ENV_APP_ID, "app"),
                ctx.job_name, ctx.task_index,
            )
            argv = containers.build_container_command(
                ctx.command, contract_env, ctx.conf,
                work_dir=ctx.work_dir, role=ctx.job_name,
                job_dir=ctx.base_child_env.get(c.ENV_JOB_DIR) or None,
                name=name,
            )
            ctx.container_name = name
            proc = subprocess.Popen(
                argv, env=dict(os.environ), cwd=ctx.work_dir or None)
            ctx.child_process = proc
            ctx.note_span("child_spawned")
        else:
            # bare tasks may adopt a pre-warmed standby (container mode
            # stays cold: the warm interpreter lives outside the image)
            proc = spawn_or_adopt(ctx, contract_env)
        try:
            return proc.wait()
        finally:
            if ctx.container_name:
                # normal exit: --rm already removed it (no-op); kill paths
                # (timeout, SIGTERM teardown): the docker CLI cannot forward
                # SIGKILL, so reap the container itself
                containers.remove_container(ctx.container_name)


class TaskContext:
    """Everything an executor-side adapter may need; filled by
    tony_tpu.executor before run()."""

    def __init__(
        self,
        job_name: str,
        task_index: int,
        task_num: int,
        num_total_tasks: int,
        is_chief: bool,
        command: str,
        cluster_payload: dict[str, Any],
        base_child_env: dict[str, str],
        rpc_client: Any = None,
        conf: "TonyConf | None" = None,
        tb_port: int | None = None,
    ):
        self.job_name = job_name
        self.task_index = task_index
        self.task_num = task_num
        self.num_total_tasks = num_total_tasks
        self.is_chief = is_chief
        self.command = command
        self.cluster_payload = cluster_payload
        self.base_child_env = base_child_env
        self.rpc_client = rpc_client
        self.conf = conf
        self.tb_port = tb_port
        self.work_dir: str | None = None
        self.child_process: subprocess.Popen | None = None
        self.container_name: str | None = None
        # executor-side lifecycle spans ([name, unix_ts] or
        # [name, unix_ts, attrs]) — adapters mark child_spawned /
        # child_adopted here; the TaskMonitor ships them to the driver,
        # which merges them into the task's TaskTrace (span attrs land
        # on the trace's attrs dict)
        self.spans: list[list] = []

    def note_span(self, name: str, attrs: dict | None = None) -> None:
        span: list = [name, time.time()]
        if attrs:
            span.append(dict(attrs))
        self.spans.append(span)

    @property
    def cluster_spec(self) -> dict[str, list[str]]:
        return self.cluster_payload.get("cluster", {})

    def global_rank(self) -> int:
        """Deterministic global rank: roles in sorted order, then index —
        every process computes the same numbering from the same spec."""
        rank = 0
        for role in sorted(self.cluster_spec):
            n = len(self.cluster_spec[role])
            if role == self.job_name:
                return rank + self.task_index
            rank += n
        return rank + self.task_index

    def world_size(self) -> int:
        return sum(len(v) for v in self.cluster_spec.values()) or self.num_total_tasks


class Runtime:
    """A named pair of adapters."""

    name: str = ""

    def driver_adapter(self) -> DriverAdapter:
        raise NotImplementedError

    def task_adapter(self) -> TaskAdapter:
        raise NotImplementedError
