"""Framework runtime SPI.

Mirrors the reference's pluggable Framework interface (Framework.java:32-71),
split the same way into a driver-side adapter (cluster-spec construction, gang
gating, config validation, health, rendezvous callbacks) and an executor-side
adapter (env building, process exec). Discovery is by registry name keyed off
``tony.application.framework`` (reference uses java.util.ServiceLoader,
FrameworkRuntimeProvider.java:30-67).
"""

from __future__ import annotations

import subprocess
import os
import time
from typing import TYPE_CHECKING, Any

from ..api import DistributedMode

if TYPE_CHECKING:
    from ..conf import TonyConf
    from ..session import Session


class DriverAdapter:
    """Driver-side behavior — reference Framework.ApplicationMasterAdapter."""

    def __init__(self) -> None:
        self.session: "Session | None" = None

    def set_session(self, session: "Session") -> None:
        self.session = session

    def validate_and_update_config(self, conf: "TonyConf") -> None:
        """Hook to inject roles / reject illegal keys before the session is
        built (reference HorovodRuntime.validateAndUpdateConfig:210-232)."""

    def can_start_task(self, mode: DistributedMode, task_id: str) -> bool:
        """The gang barrier: may `task_id` receive its cluster spec yet?
        (reference MLGenericRuntime.java:80-98)."""
        raise NotImplementedError

    def cluster_spec_payload(self, task_id: str) -> dict[str, Any]:
        """What register_worker/get_cluster_spec returns once the barrier
        opens. Base payload is the role->addresses map plus any named
        service ports tasks have published (publish_ports RPC — the
        generalization of the reference's TF_CONFIG endpoint plumbing);
        runtimes add their rendezvous data (reference
        constructClusterSpec)."""
        assert self.session is not None
        payload: dict[str, Any] = {"cluster": self.session.cluster_spec()}
        # which elastic gang formation this spec describes — bumped by
        # every resize, so an executor/tooling can tell a re-formed
        # (smaller or restored) gang from the one it first joined
        payload["gang_generation"] = self.session.gang_generation
        ports = self.session.service_ports()
        if ports:
            payload["service_ports"] = ports
        return payload

    def is_healthy(self, conf: "TonyConf") -> bool:
        """Periodic health check from the driver monitor loop (reference
        allocation-timeout deadlock breaker, MLGenericRuntime.java:110-147)."""
        return True

    def receive_callback_info(self, task_id: str, payload: dict[str, Any]) -> None:
        """Runtime rendezvous callbacks (reference receiveTaskCallbackInfo)."""


class TaskAdapter:
    """Executor-side behavior — reference Framework.TaskExecutorAdapter."""

    def need_tb_port(self) -> bool:
        return False

    def build_env(self, ctx: "TaskContext") -> dict[str, str]:
        """Map the cluster-spec payload into the env contract the user's
        training process expects."""
        raise NotImplementedError

    def run(self, ctx: "TaskContext") -> int:
        """Default: fork the user command through a shell with the built env,
        stream output, return its exit code (reference
        Utils.executeShell:299-328 — minus the hadoop-classpath preamble,
        which has no TPU equivalent). With `tony.docker.enabled` the command
        runs inside the configured image instead (reference Docker-on-YARN,
        HadoopCompatibleAdapter.java:45-159)."""
        from .. import constants as c
        from ..utils import containers

        contract_env = {**ctx.base_child_env, **self.build_env(ctx)}
        if containers.container_enabled(ctx.conf):
            # execution-env / role-env vars reach bare tasks via os.environ
            # inheritance; containers need them forwarded explicitly
            contract_env = {
                **containers.passthrough_env(ctx.conf, ctx.job_name),
                **contract_env,
            }
            name = containers.container_name(
                ctx.base_child_env.get(c.ENV_APP_ID, "app"),
                ctx.job_name, ctx.task_index,
            )
            argv = containers.build_container_command(
                ctx.command, contract_env, ctx.conf,
                work_dir=ctx.work_dir, role=ctx.job_name,
                job_dir=ctx.base_child_env.get(c.ENV_JOB_DIR) or None,
                name=name,
            )
            ctx.container_name = name
            env = dict(os.environ)
        else:
            argv = ["bash", "-c", ctx.command]
            env = {**os.environ, **contract_env}
        proc = subprocess.Popen(argv, env=env, cwd=ctx.work_dir or None)
        ctx.child_process = proc
        ctx.note_span("child_spawned")
        try:
            return proc.wait()
        finally:
            if ctx.container_name:
                # normal exit: --rm already removed it (no-op); kill paths
                # (timeout, SIGTERM teardown): the docker CLI cannot forward
                # SIGKILL, so reap the container itself
                containers.remove_container(ctx.container_name)


class TaskContext:
    """Everything an executor-side adapter may need; filled by
    tony_tpu.executor before run()."""

    def __init__(
        self,
        job_name: str,
        task_index: int,
        task_num: int,
        num_total_tasks: int,
        is_chief: bool,
        command: str,
        cluster_payload: dict[str, Any],
        base_child_env: dict[str, str],
        rpc_client: Any = None,
        conf: "TonyConf | None" = None,
        tb_port: int | None = None,
    ):
        self.job_name = job_name
        self.task_index = task_index
        self.task_num = task_num
        self.num_total_tasks = num_total_tasks
        self.is_chief = is_chief
        self.command = command
        self.cluster_payload = cluster_payload
        self.base_child_env = base_child_env
        self.rpc_client = rpc_client
        self.conf = conf
        self.tb_port = tb_port
        self.work_dir: str | None = None
        self.child_process: subprocess.Popen | None = None
        self.container_name: str | None = None
        # executor-side lifecycle spans ([name, unix_ts]) — adapters mark
        # child_spawned here; the TaskMonitor ships them to the driver,
        # which merges them into the task's TaskTrace
        self.spans: list[list] = []

    def note_span(self, name: str) -> None:
        self.spans.append([name, time.time()])

    @property
    def cluster_spec(self) -> dict[str, list[str]]:
        return self.cluster_payload.get("cluster", {})

    def global_rank(self) -> int:
        """Deterministic global rank: roles in sorted order, then index —
        every process computes the same numbering from the same spec."""
        rank = 0
        for role in sorted(self.cluster_spec):
            n = len(self.cluster_spec[role])
            if role == self.job_name:
                return rank + self.task_index
            rank += n
        return rank + self.task_index

    def world_size(self) -> int:
        return sum(len(v) for v in self.cluster_spec.values()) or self.num_total_tasks


class Runtime:
    """A named pair of adapters."""

    name: str = ""

    def driver_adapter(self) -> DriverAdapter:
        raise NotImplementedError

    def task_adapter(self) -> TaskAdapter:
        raise NotImplementedError
