"""Ray runtime adapter: head/worker roles with head-address bootstrap.

The reference runs Ray through generic roles + a user-side discovery script
(tony-examples/ray-on-tony: tony.head.command / tony.worker.command, README
config block). Here it is a first-class runtime: the ``head`` role's
registered address is exported to every task as RAY_HEAD_ADDRESS /
RAY_ADDRESS, so worker commands can be plain ``ray start
--address=$RAY_ADDRESS --block`` with no discovery sidecar.
"""

from __future__ import annotations

from ..api import DistributedMode
from .base import TaskContext
from .generic import GenericDriverAdapter, GenericTaskAdapter

HEAD_ROLE = "head"


class RayDriverAdapter(GenericDriverAdapter):
    def validate_and_update_config(self, conf) -> None:
        from ..conf import keys

        if conf.get_int(keys.instances_key(HEAD_ROLE), 0) != 1:
            raise ValueError("ray runtime requires exactly one 'head' instance")

    def can_start_task(self, mode: DistributedMode, task_id: str) -> bool:
        assert self.session is not None
        if task_id.startswith(HEAD_ROLE + ":"):
            return True  # head starts immediately; it IS the rendezvous
        if mode == DistributedMode.GANG:
            return self.session.all_registered()
        # FCFS workers still need the head's address
        return bool(self.session.cluster_spec().get(HEAD_ROLE))


class RayTaskAdapter(GenericTaskAdapter):
    def build_env(self, ctx: TaskContext) -> dict[str, str]:
        env = super().build_env(ctx)
        head = ctx.cluster_spec.get(HEAD_ROLE, [])
        if head:
            env["RAY_HEAD_ADDRESS"] = head[0]
            env["RAY_ADDRESS"] = head[0]
            host, port = head[0].rsplit(":", 1)
            env["RAY_HEAD_IP"] = host
            env["RAY_HEAD_PORT"] = port
        return env
