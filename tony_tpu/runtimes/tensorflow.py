"""TensorFlow runtime adapter: CLUSTER_SPEC + TF_CONFIG env.

Mirrors TFRuntime.java:45-58 and Utils.constructTFConfig (util/Utils.java:
503-520): TF_CONFIG = {"cluster": {role: [addrs]}, "task": {"type", "index"}}
with the sidecar/eval roles (tensorboard) excluded from the cluster dict so
estimator-style code doesn't wait on them.
"""

from __future__ import annotations

import json

from .base import TaskContext
from .generic import GenericDriverAdapter, GenericTaskAdapter

# roles never included in TF cluster spec (reference filters evaluator/
# tensorboard when building TF_CONFIG's cluster dict, util/Utils.java:503-520
# — the evaluator still gets TF_CONFIG with its own task type, it just isn't
# part of the cluster the other tasks wait on)
_EXCLUDED_FROM_CLUSTER = ("tensorboard", "evaluator")


class TFDriverAdapter(GenericDriverAdapter):
    pass


class TFTaskAdapter(GenericTaskAdapter):
    def need_tb_port(self) -> bool:
        return True

    def build_env(self, ctx: TaskContext) -> dict[str, str]:
        env = super().build_env(ctx)
        cluster = {
            role: addrs
            for role, addrs in ctx.cluster_spec.items()
            if role not in _EXCLUDED_FROM_CLUSTER
        }
        tf_config = {
            "cluster": cluster,
            "task": {"type": ctx.job_name, "index": ctx.task_index},
        }
        env["TF_CONFIG"] = json.dumps(tf_config)
        env["JOB_NAME"] = ctx.job_name
        env["TASK_INDEX"] = str(ctx.task_index)
        return env
