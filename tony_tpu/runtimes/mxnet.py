"""MXNet runtime adapter: DMLC PS-Lite env.

Mirrors MXNetRuntime.java:43-66 + Utils.parseClusterSpecForMXNet
(util/Utils.java:618-640): the 'scheduler' task's address becomes
DMLC_PS_ROOT_URI/PORT for every task; DMLC_ROLE is the task's own role;
server/worker counts are taken from the cluster spec.
"""

from __future__ import annotations

import socket

from .base import TaskContext
from .generic import GenericDriverAdapter, GenericTaskAdapter


class MXNetDriverAdapter(GenericDriverAdapter):
    pass


class MXNetTaskAdapter(GenericTaskAdapter):
    def build_env(self, ctx: TaskContext) -> dict[str, str]:
        env = super().build_env(ctx)
        spec = ctx.cluster_spec
        scheduler = spec.get("scheduler", [])
        if not scheduler:
            raise RuntimeError("mxnet runtime requires a 'scheduler' role")
        host, port = scheduler[0].rsplit(":", 1)
        try:
            # reference resolves hostname -> IP (Utils.java:618-640)
            host_ip = socket.gethostbyname(host)
        except OSError:
            host_ip = host
        env.update({
            "DMLC_ROLE": ctx.job_name,
            "DMLC_PS_ROOT_URI": host_ip,
            "DMLC_PS_ROOT_PORT": port,
            "DMLC_NUM_SERVER": str(len(spec.get("server", []))),
            "DMLC_NUM_WORKER": str(len(spec.get("worker", []))),
        })
        return env
