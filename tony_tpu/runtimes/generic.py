"""Shared GANG/FCFS gating + allocation-timeout health check.

Mirrors MLGenericRuntime.java: in GANG mode no task gets its cluster spec
until every instance of every role has registered (:80-98); the allocation
-timeout health check breaks gang deadlocks when capacity never arrives
(:110-147, reference issue #573).
"""

from __future__ import annotations

import time
from typing import Any

from ..api import DistributedMode
from ..conf import TonyConf, keys
from .base import DriverAdapter, TaskAdapter, TaskContext


class GenericDriverAdapter(DriverAdapter):
    def __init__(self) -> None:
        super().__init__()
        self._first_request_ms: float | None = None

    def note_requests_submitted(self) -> None:
        if self._first_request_ms is None:
            self._first_request_ms = time.time() * 1000

    def can_start_task(self, mode: DistributedMode, task_id: str) -> bool:
        assert self.session is not None
        if mode == DistributedMode.FCFS:
            return True
        return self.session.all_registered()

    def is_healthy(self, conf: TonyConf) -> bool:
        timeout_ms = conf.get_int(keys.AM_ALLOCATION_TIMEOUT_MS, 0)
        if timeout_ms <= 0 or self._first_request_ms is None or self.session is None:
            return True
        # Unhealthy iff some requested task never got capacity within the
        # timeout while the gang waits.
        from ..api import TaskStatus

        waiting = [
            t for t in self.session.all_tasks()
            if t.status in (TaskStatus.NEW, TaskStatus.REQUESTED)
        ]
        if not waiting:
            return True
        return (time.time() * 1000 - self._first_request_ms) < timeout_ms


class GenericTaskAdapter(TaskAdapter):
    """Exports the generic contract: CLUSTER_SPEC JSON + rank/world —
    enough for any framework that can read a phone book."""

    def build_env(self, ctx: TaskContext) -> dict[str, str]:
        import json

        from .. import constants as c

        env = {
            c.ENV_CLUSTER_SPEC: json.dumps(ctx.cluster_spec),
            c.ENV_GANG_GENERATION: str(
                ctx.cluster_payload.get("gang_generation", 0)),
        }
        if ctx.tb_port is not None:
            env[c.ENV_TB_PORT] = str(ctx.tb_port)
        return env


class StandaloneDriverAdapter(GenericDriverAdapter):
    """Single-task mode: no cluster spec, no gang (reference
    StandaloneRuntime.java:69-99 — rejects multi-instance configs)."""

    def validate_and_update_config(self, conf: TonyConf) -> None:
        specs = conf.role_specs()
        total = sum(s.instances for s in specs)
        if total != 1:
            raise ValueError(
                f"standalone runtime requires exactly 1 task, got {total}"
            )

    def can_start_task(self, mode: DistributedMode, task_id: str) -> bool:
        return True

    def cluster_spec_payload(self, task_id: str) -> dict[str, Any]:
        return {"cluster": {}}


class StandaloneTaskAdapter(TaskAdapter):
    def build_env(self, ctx: TaskContext) -> dict[str, str]:
        return {}
