"""JAX runtime — the primary, TPU-native path.

Replaces the reference's entire TF_CONFIG/Gloo/c10d/DMLC bootstrap matrix with
one contract (SURVEY.md §5 "distributed communication backend"): the driver
collects worker registrations, elects the process with global rank 0 as the
coordinator, and every executor exports

    TONY_COORDINATOR_ADDRESS  host:port of rank 0's pre-bound coordinator port
    TONY_PROCESS_ID           this process's global rank
    TONY_NUM_PROCESSES        world size

User code calls ``tony_tpu.init()`` (train/bootstrap.py) which reads these and
invokes ``jax.distributed.initialize``; collectives then ride ICI within the
slice and DCN across slices inside XLA. The registered host:port plays the
role the reference's registerWorkerSpec host:port plays for TF
(TonySession.getClusterSpec:235-255) — except here the port is a real
pre-reserved TCP port the coordinator service will bind.
"""

from __future__ import annotations

import json
from typing import Any

from .. import constants as c
from .base import TaskContext
from .generic import GenericDriverAdapter, GenericTaskAdapter


class JaxDriverAdapter(GenericDriverAdapter):
    def cluster_spec_payload(self, task_id: str) -> dict[str, Any]:
        assert self.session is not None
        spec = self.session.cluster_spec()
        payload: dict[str, Any] = {"cluster": spec}
        ranks: dict[str, int] = {}
        rank = 0
        coordinator = None
        # rank by REAL task identity, not list position: an elastically
        # resized gang's address lists are COMPACTED (detached slots
        # removed), so for e.g. workers {0, 2} the position-keyed scheme
        # would label worker:2's entry "worker:1" and leave worker:2
        # falling back to a rank >= num_processes — the re-formed gang
        # could never initialize. registered_tasks() walks the same
        # index order cluster_spec() used, so rank i is address i.
        for role in sorted(spec):
            for t in self.session.registered_tasks(role):
                ranks[t.task_id] = rank
                if rank == 0:
                    coordinator = t.address
                rank += 1
        payload["ranks"] = ranks
        payload["num_processes"] = rank
        payload["coordinator_address"] = coordinator
        payload["gang_generation"] = self.session.gang_generation
        return payload


class JaxTaskAdapter(GenericTaskAdapter):
    def need_tb_port(self) -> bool:
        return False

    def build_env(self, ctx: TaskContext) -> dict[str, str]:
        env = super().build_env(ctx)
        payload = ctx.cluster_payload
        task_id = f"{ctx.job_name}:{ctx.task_index}"
        rank = payload.get("ranks", {}).get(task_id, ctx.global_rank())
        env.update({
            c.ENV_COORDINATOR_ADDRESS: str(payload.get("coordinator_address") or ""),
            c.ENV_PROCESS_ID: str(rank),
            c.ENV_NUM_PROCESSES: str(payload.get("num_processes", ctx.world_size())),
        })
        # multislice: the provisioner stamped TONY_SLICE_ID/NUM_SLICES/
        # SLICE0_HOST into this executor's env from its capacity topology;
        # map them to libtpu's MEGASCALE_* vars so DCN transport comes up
        # across slices. jax.distributed.initialize still uses the single
        # TONY coordinator for the control plane — the same one-coordinator
        # contract, now spanning slices.
        import os

        n_slices = int(os.environ.get(c.ENV_NUM_SLICES, "1") or 1)
        if n_slices > 1:
            slice0 = os.environ.get(c.ENV_SLICE0_HOST, "")
            if not slice0:
                # Without this, MEGASCALE_COORDINATOR_ADDRESS would be the
                # malformed ":port" and libtpu would fail much later with an
                # opaque transport error.
                raise RuntimeError(
                    f"{c.ENV_NUM_SLICES}={n_slices} but {c.ENV_SLICE0_HOST} "
                    "is unset/empty; the multislice provisioner must stamp "
                    "the slice-0 host so DCN transport can rendezvous"
                )
            env.update({
                "MEGASCALE_NUM_SLICES": str(n_slices),
                "MEGASCALE_SLICE_ID": os.environ.get(c.ENV_SLICE_ID, "0"),
                "MEGASCALE_COORDINATOR_ADDRESS":
                    f"{slice0}:{c.MEGASCALE_PORT}",
            })
        return env
