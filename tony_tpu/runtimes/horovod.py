"""Horovod runtime adapter: two-phase gang with an injected rendezvous driver.

Mirrors HorovodRuntime.java:87-350 + HorovodDriver.java + horovod_driver.py:
1. config validation injects an untracked ``driver`` role (validateAndUpdateConfig:210-232)
2. the driver task starts once all tasks registered; its payload is the worker
   host list (constructClusterSpec:87-120)
3. the driver task computes the slot table (rank/local_rank/cross_rank/sizes
   — the reference delegates to horovod's get_host_assignments; here the same
   assignment is computed natively, see compute_slot_assignments), starts a
   Gloo rendezvous server (horovod's if importable, else a stub in test mode),
   and reports {addr, port, slots} back over register_callback_info
   (receiveTaskCallbackInfo:161-178)
4. workers block in can_start_task until the callback lands, then get
   HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT + per-slot HOROVOD_* env
   (setHorovodRunEnv:312-350).
"""

from __future__ import annotations

import json
import logging
import socket
import threading
import time
from dataclasses import asdict, dataclass
from typing import Any

from ..api import DistributedMode
from ..conf import TonyConf, keys
from .base import TaskContext
from .generic import GenericDriverAdapter, GenericTaskAdapter

log = logging.getLogger(__name__)

DRIVER_ROLE = "driver"
HOROVOD_TEST_MODE_KEY = keys.HOROVOD_TEST_MODE  # reference HorovodRuntime.java:298-310


@dataclass
class SlotInfo:
    """Reference horovod/SlotInfo.java."""

    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int


def compute_slot_assignments(host_slots: list[tuple[str, int]]) -> list[SlotInfo]:
    """Host-major rank assignment identical to horovod's get_host_assignments:
    rank increments host by host; local_rank is the slot index on its host;
    cross_rank is the host's position among hosts owning that local_rank."""
    total = sum(n for _, n in host_slots)
    slots: list[SlotInfo] = []
    rank = 0
    for host_idx, (host, n) in enumerate(host_slots):
        for local_rank in range(n):
            cross_hosts = [h for h, m in host_slots if m > local_rank]
            slots.append(
                SlotInfo(
                    hostname=host,
                    rank=rank,
                    local_rank=local_rank,
                    cross_rank=cross_hosts.index(host),
                    size=total,
                    local_size=n,
                    cross_size=len(cross_hosts),
                )
            )
            rank += 1
    return slots


class HorovodDriverAdapter(GenericDriverAdapter):
    def __init__(self) -> None:
        super().__init__()
        self._callback: dict[str, Any] | None = None
        self._lock = threading.Lock()

    def validate_and_update_config(self, conf: TonyConf) -> None:
        if conf.get_int(keys.instances_key(DRIVER_ROLE), 0) == 0:
            conf.set(keys.instances_key(DRIVER_ROLE), 1)
        untracked = set(conf.get_list(keys.APPLICATION_UNTRACKED_JOBTYPES))
        untracked.add(DRIVER_ROLE)
        conf.set(keys.APPLICATION_UNTRACKED_JOBTYPES, ",".join(sorted(untracked)))

    def can_start_task(self, mode: DistributedMode, task_id: str) -> bool:
        assert self.session is not None
        if task_id.startswith(DRIVER_ROLE + ":"):
            # phase 1: rendezvous driver starts when everyone registered
            return self.session.all_registered()
        # phase 2: workers wait for the driver's callback
        with self._lock:
            return self._callback is not None

    def receive_callback_info(self, task_id: str, payload: dict[str, Any]) -> None:
        with self._lock:
            self._callback = payload

    def cluster_spec_payload(self, task_id: str) -> dict[str, Any]:
        assert self.session is not None
        payload = super().cluster_spec_payload(task_id)
        if task_id.startswith(DRIVER_ROLE + ":"):
            # worker host list with slot counts, e.g. [["h1", 2], ["h2", 1]]
            counts: dict[str, int] = {}
            for addr in payload["cluster"].get("worker", []):
                host = addr.rsplit(":", 1)[0]
                counts[host] = counts.get(host, 0) + 1
            payload["worker_hosts"] = sorted(counts.items())
        else:
            with self._lock:
                payload["rendezvous"] = dict(self._callback or {})
        return payload


class _StubRendezvousServer:
    """Accept-and-hold TCP server standing in for horovod's RendezvousServer
    when horovod isn't installed (reference test mode,
    horovod_driver.py:44-65)."""

    def __init__(self) -> None:
        self._sock = socket.socket()
        self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(64)
        self.port = self._sock.getsockname()[1]
        threading.Thread(target=self._loop, daemon=True).start()

    def _loop(self) -> None:
        while True:
            try:
                conn, _ = self._sock.accept()
                conn.close()
            except OSError:
                return

    def close(self) -> None:
        self._sock.close()


class HorovodTaskAdapter(GenericTaskAdapter):
    def run(self, ctx: TaskContext) -> int:
        if ctx.job_name == DRIVER_ROLE:
            return self._run_rendezvous_driver(ctx)
        return super().run(ctx)

    # ------------------------------------------------------- driver task path
    def _run_rendezvous_driver(self, ctx: TaskContext) -> int:
        if ctx.conf and ctx.conf.get_bool(keys.HOROVOD_FAST_FAIL):
            # reference horovod_driver.py's -f flag: simulate the rendezvous
            # server crashing before any callback — exercises untracked-task
            # fast-fail in the driver monitor
            log.error("horovod driver fast-fail requested; exiting")
            return 1
        host_slots = [tuple(x) for x in ctx.cluster_payload.get("worker_hosts", [])]
        if not host_slots:
            log.error("horovod driver got empty worker host list")
            return 1
        slots = compute_slot_assignments(host_slots)
        debug_cmd = str(ctx.conf.get(keys.HOROVOD_DEBUG_COMMAND, "") or "") if ctx.conf else ""
        addr = ""
        if debug_cmd:
            addr, port = self._start_debug_rendezvous(ctx, debug_cmd)
        else:
            test_mode = bool(ctx.conf and ctx.conf.get_bool(HOROVOD_TEST_MODE_KEY))
            port = self._start_rendezvous(host_slots, slots, test_mode)
        if port < 0:
            return 1
        ctx.rpc_client.call(
            "register_callback_info",
            task_id=f"{ctx.job_name}:{ctx.task_index}",
            payload={
                "addr": addr or socket.gethostbyname(socket.gethostname()),
                "port": port,
                "slots": [asdict(s) for s in slots],
            },
        )
        # stay alive while training runs; the driver is untracked so the job
        # completes without it (reference: driver waitFor ends with rendezvous)
        while True:
            time.sleep(3600)

    def _start_debug_rendezvous(self, ctx: TaskContext, debug_cmd: str) -> tuple[str, int]:
        """User-supplied rendezvous driver (reference debug driver command,
        HorovodDriver.java:189-216): fork the command with
        HOROVOD_RDV_INFO_FILE pointing at a marker path, then poll that file
        for the {"port": N[, "addr": host]} JSON the command writes once its
        server is up — the same marker-file dance as the reference's
        '<port>____HOROVOD_RENDEZVOUS_SERVER____' poll (HorovodDriver.java:128-183).
        Returns ("" | published addr, port); port < 0 on failure."""
        import os
        import subprocess
        import tempfile

        marker = os.path.join(
            ctx.work_dir or tempfile.mkdtemp(prefix="tony-hvd-"),
            f"rendezvous_{ctx.task_index}.json",
        )
        try:
            os.remove(marker)  # a stale marker would publish a dead port
        except OSError:
            pass
        env = {**os.environ, **ctx.base_child_env, "HOROVOD_RDV_INFO_FILE": marker}
        self._debug_proc = subprocess.Popen(["bash", "-c", debug_cmd], env=env)
        timeout_s = (
            ctx.conf.get_int(keys.HOROVOD_DRIVER_START_TIMEOUT_MS, 60000) / 1000
            if ctx.conf else 60.0
        )
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if os.path.exists(marker):
                try:
                    info = json.loads(open(marker).read())
                    return str(info.get("addr", "")), int(info["port"])
                except (ValueError, KeyError, TypeError):
                    pass  # partially written; keep polling
            if self._debug_proc.poll() is not None:
                log.error("debug rendezvous driver exited %d before publishing",
                          self._debug_proc.returncode)
                return "", -1
            time.sleep(0.2)
        log.error("debug rendezvous driver did not publish within %.0fs", timeout_s)
        self._debug_proc.kill()
        return "", -1

    def _start_rendezvous(self, host_slots, slots, test_mode: bool) -> int:
        if not test_mode:
            try:
                from horovod.runner.common.util.hosts import (
                    parse_hosts, get_host_assignments,
                )
                from horovod.runner.http.http_server import RendezvousServer

                host_str = ",".join(f"{h}:{n}" for h, n in host_slots)
                hosts = parse_hosts(host_str)
                assignments = get_host_assignments(hosts, 1)
                server = RendezvousServer()
                port = server.start()
                # the server must be initialised with the host plan or
                # workers can never rendezvous — reference
                # horovod_driver.py:32-42 (static_driver_fn)
                server.init(assignments)
                self._real_server = server  # keep the server alive
                return port
            except ImportError:
                log.warning("horovod not installed; using stub rendezvous server")
        self._stub = _StubRendezvousServer()
        return self._stub.port

    # ------------------------------------------------------ worker task path
    def build_env(self, ctx: TaskContext) -> dict[str, str]:
        env = super().build_env(ctx)
        if ctx.job_name == DRIVER_ROLE:
            return env
        rdv = ctx.cluster_payload.get("rendezvous", {})
        slots = [SlotInfo(**s) for s in rdv.get("slots", [])]
        my_addr = ctx.cluster_spec.get(ctx.job_name, [])
        my_host = (
            my_addr[ctx.task_index].rsplit(":", 1)[0]
            if ctx.task_index < len(my_addr) else ""
        )
        slot = self._pick_slot(slots, my_host, ctx)
        env.update({
            "HOROVOD_CONTROLLER": "gloo",
            "HOROVOD_CPU_OPERATIONS": "gloo",
            "HOROVOD_GLOO_RENDEZVOUS_ADDR": str(rdv.get("addr", "")),
            "HOROVOD_GLOO_RENDEZVOUS_PORT": str(rdv.get("port", "")),
            "HOROVOD_RANK": str(slot.rank),
            "HOROVOD_SIZE": str(slot.size),
            "HOROVOD_LOCAL_RANK": str(slot.local_rank),
            "HOROVOD_LOCAL_SIZE": str(slot.local_size),
            "HOROVOD_CROSS_RANK": str(slot.cross_rank),
            "HOROVOD_CROSS_SIZE": str(slot.cross_size),
            "HOROVOD_HOSTNAME": slot.hostname,
        })
        return env

    @staticmethod
    def _pick_slot(slots: list[SlotInfo], my_host: str, ctx: TaskContext) -> SlotInfo:
        """Assign this worker a slot on its own host: workers on a host are
        ordered by task index, slots by local_rank (reference
        setHorovodRunEnv:312-350)."""
        if not slots:
            raise RuntimeError("no horovod slots in rendezvous payload")
        on_host = [s for s in slots if s.hostname == my_host] or slots
        peers_before = 0
        for i, addr in enumerate(ctx.cluster_spec.get(ctx.job_name, [])):
            if i >= ctx.task_index:
                break
            if addr.rsplit(":", 1)[0] == my_host:
                peers_before += 1
        return on_host[peers_before % len(on_host)]
