"""Runtime registry.

Reference equivalent: java.util.ServiceLoader over
META-INF/services/com.linkedin.tony.AbstractFrameworkRuntime keyed by
``tony.application.framework`` (FrameworkRuntimeProvider.java:30-67,
TonyConfigurationKeys.FrameworkType). Python entry-point-style registration:
a dict, extensible at runtime via register_runtime().
"""

from __future__ import annotations

from .base import DriverAdapter, Runtime, TaskAdapter, TaskContext
from .generic import (
    GenericDriverAdapter,
    GenericTaskAdapter,
    StandaloneDriverAdapter,
    StandaloneTaskAdapter,
)
from .horovod import HorovodDriverAdapter, HorovodTaskAdapter
from .jax_runtime import JaxDriverAdapter, JaxTaskAdapter
from .mxnet import MXNetDriverAdapter, MXNetTaskAdapter
from .pytorch import PyTorchDriverAdapter, PyTorchTaskAdapter
from .ray import RayDriverAdapter, RayTaskAdapter
from .serving import (
    RouterTaskAdapter,
    ServingDriverAdapter,
    ServingTaskAdapter,
)
from .tensorflow import TFDriverAdapter, TFTaskAdapter


class _SimpleRuntime(Runtime):
    def __init__(self, name: str, driver_cls, task_cls):
        self.name = name
        self._driver_cls = driver_cls
        self._task_cls = task_cls

    def driver_adapter(self) -> DriverAdapter:
        return self._driver_cls()

    def task_adapter(self) -> TaskAdapter:
        return self._task_cls()


_REGISTRY: dict[str, Runtime] = {}


def register_runtime(runtime: Runtime) -> None:
    _REGISTRY[runtime.name] = runtime


for _name, _d, _t in (
    ("jax", JaxDriverAdapter, JaxTaskAdapter),
    ("tensorflow", TFDriverAdapter, TFTaskAdapter),
    ("pytorch", PyTorchDriverAdapter, PyTorchTaskAdapter),
    ("mxnet", MXNetDriverAdapter, MXNetTaskAdapter),
    ("horovod", HorovodDriverAdapter, HorovodTaskAdapter),
    ("ray", RayDriverAdapter, RayTaskAdapter),
    ("serving", ServingDriverAdapter, ServingTaskAdapter),
    # the router tier is supervised exactly like serving replicas —
    # same driver adapter (no gang barrier), a task adapter that skips
    # the serve-flag templating (docs/serving.md "Router tier HA")
    ("router", ServingDriverAdapter, RouterTaskAdapter),
    ("standalone", StandaloneDriverAdapter, StandaloneTaskAdapter),
    ("generic", GenericDriverAdapter, GenericTaskAdapter),
):
    register_runtime(_SimpleRuntime(_name, _d, _t))


def get_runtime(name: str) -> Runtime:
    try:
        return _REGISTRY[name.lower()]
    except KeyError:
        raise ValueError(
            f"unknown framework runtime {name!r}; known: {sorted(_REGISTRY)}"
        ) from None


__all__ = [
    "DriverAdapter",
    "TaskAdapter",
    "TaskContext",
    "Runtime",
    "get_runtime",
    "register_runtime",
]
