"""Serving runtime — SlotServer fleets as a first-class job type.

The composition PAPER.md's L4/L2 pattern was built for: the driver
(ApplicationMaster role) gang-launches N replicas of the hardened
inference server (``tony-tpu serve``, cli/serve.py) as ordinary tasks.
Each replica's executor runs this adapter, which

- exports ``TONY_SERVE_PORT`` (= the task's registered rendezvous port,
  the same port the notebook runtime hands its child) so the role
  command binds a port the driver already knows;
- spawns the serve child and watches its ``/healthz``;
- on the FIRST healthy poll marks a ``serving_ready`` span on the task
  trace and advertises ``serve_port``/``metrics_port`` through the
  ``publish_ports`` RPC — they land in the cluster spec, on
  get_task_infos (where the fleet router's discovery reads them), and
  as ``driver_task_service_port`` gauges on the driver /metrics;
- converts a terminally DOWN serving loop (``/healthz`` 503 for
  ``tony.serving.healthz-down-polls`` consecutive polls after ready)
  into a container failure: kill the child, exit nonzero, and the
  driver's per-task restart budget relaunches the replica — the replica
  chain shows up in tasks.trace.jsonl like any task.

Replicas are independent servers, so the gang barrier is a formality:
``can_start_task`` always passes and each replica starts serving the
moment it is up (a fleet warms replica-by-replica instead of holding
every ready server hostage to the slowest compile).

Weight updates roll through the driver's ``roll_task`` RPC: SIGTERM
reaches the replica's process group, the serve child drains in-flight
requests (cli/serve.py's drain handler), and the driver relaunches the
task budget-free — the new process loads the updated checkpoint. See
docs/serving.md "Fleet serving".
"""

from __future__ import annotations

import logging
import os
import subprocess
import time
import urllib.error
import urllib.request

from .. import constants as c
from ..conf import keys
from .base import TaskAdapter, TaskContext
from .generic import GenericDriverAdapter

log = logging.getLogger(__name__)


def _kill_tree(proc: subprocess.Popen) -> None:
    """SIGKILL ``proc`` and every /proc-visible descendant. The role
    command runs under ``bash -c``: a compound command forks instead of
    exec'ing, and killing only the bash would orphan the serve
    grandchild — still bound to the old port, still answering /healthz —
    while the driver relaunches the replica. A new session/process group
    is NOT an option here: the provisioner's group SIGTERM is how the
    serve child learns to drain (rolls) and how job teardown reaps it."""
    victims = {proc.pid}
    try:
        children: dict[int, list[int]] = {}
        for entry in os.listdir("/proc"):
            if not entry.isdigit():
                continue
            try:
                with open(f"/proc/{entry}/stat") as f:
                    ppid = int(f.read().split()[3])
            except (OSError, IndexError, ValueError):
                continue
            children.setdefault(ppid, []).append(int(entry))
        stack = [proc.pid]
        while stack:
            pid = stack.pop()
            for child in children.get(pid, []):
                if child not in victims:
                    victims.add(child)
                    stack.append(child)
    except OSError:
        pass        # no /proc: the direct child is the best we can do
    for pid in victims:
        try:
            os.kill(pid, 9)
        except (ProcessLookupError, PermissionError):
            pass


class ServingDriverAdapter(GenericDriverAdapter):
    """Replicas are independent: no gang barrier — a registered replica
    gets its cluster spec (and starts serving) immediately."""

    def can_start_task(self, mode, task_id: str) -> bool:
        return True


class ServingTaskAdapter(TaskAdapter):
    """Executor-side supervisor of one SlotServer replica child."""

    def need_tb_port(self) -> bool:
        return False

    def build_env(self, ctx: TaskContext) -> dict[str, str]:
        import json

        env = {
            c.ENV_CLUSTER_SPEC: json.dumps(ctx.cluster_spec),
            c.ENV_SERVE_PORT: ctx.base_child_env.get(c.ENV_TASK_PORT, ""),
        }
        flags = " ".join(part for part in (
            self._conf_serve_flags(ctx.conf),
            self._role_flags(ctx.conf, ctx.task_index)) if part)
        if flags:
            env[c.ENV_SERVE_EXTRA_FLAGS] = flags
        return env

    @staticmethod
    def _role_flags(conf, task_index) -> str:
        """Phase-tier assignment for disaggregated serving (docs/
        serving.md "Disaggregated serving"): with ``tony.serving.
        prefill-instances`` = P and ``decode-instances`` = D, the
        first P task indices launch as prefill specialists and the
        next D as decode replicas — both tiers force ``--paged-kv``
        (the KV block is the transfer unit on either side of
        /kv/import) — and the remainder stay classic ``both``
        engines. P = D = 0 (default) templates nothing: a uniform
        fleet, today's behavior."""
        if conf is None or task_index is None:
            return ""
        n_prefill = max(0, conf.get_int(keys.SERVING_PREFILL_INSTANCES, 0))
        n_decode = max(0, conf.get_int(keys.SERVING_DECODE_INSTANCES, 0))
        if not n_prefill and not n_decode:
            return ""
        idx = int(task_index)
        if idx < n_prefill:
            return "--role prefill --paged-kv"
        if idx < n_prefill + n_decode:
            return "--role decode --paged-kv"
        return "--role both"

    @staticmethod
    def _conf_serve_flags(conf) -> str:
        """Template the paged-KV serve flags from ``tony.serving.*``
        conf keys (docs/serving.md "Paged KV & admission tiers") into
        one space-separated string the child exports as
        TONY_SERVE_EXTRA_FLAGS — cli/serve.py prepends it to argv, so
        a job file flips the whole fleet to paged admission without
        editing every replica command (explicit flags still win)."""
        if conf is None:
            return ""
        flags: list[str] = []
        if conf.get_bool(keys.SERVING_PAGED_KV, False):
            flags.append("--paged-kv")
        for key, flag in (
                (keys.SERVING_KV_BLOCK, "--kv-block"),
                (keys.SERVING_KV_POOL_BLOCKS, "--kv-pool-blocks"),
                (keys.SERVING_PREFILL_INTERLEAVE,
                 "--prefill-interleave"),
                (keys.SERVING_CLASS_BUDGET_INTERACTIVE,
                 "--class-budget-interactive"),
                (keys.SERVING_CLASS_BUDGET_BATCH,
                 "--class-budget-batch")):
            val = conf.get_int(key, 0)
            if val:
                flags.extend([flag, str(val)])
        frac = conf.get(keys.SERVING_BATCH_QUEUE_FRAC, "")
        if frac:
            flags.extend(["--batch-queue-frac", str(frac)])
        return " ".join(flags)

    # ------------------------------------------------------------ health
    def _poll_healthz(self, port: int, timeout: float = 2.0) -> str:
        """One /healthz probe: "ok" (HTTP 200), "down" (HTTP 503 — the
        loop is down or draining), or "unreachable" (nothing listening /
        timed out)."""
        url = f"http://127.0.0.1:{port}/healthz"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                return "ok" if resp.status == 200 else "down"
        except urllib.error.HTTPError as e:
            return "down" if e.code == 503 else "unreachable"
        except Exception:
            return "unreachable"

    def _publish_ports(self, ctx: TaskContext, port: int) -> None:
        """Advertise the replica's endpoints. The serve process exposes
        /generate, /stats, and /metrics on ONE port, so serve_port and
        metrics_port coincide today; both names are published so the
        contract survives a future split."""
        if ctx.rpc_client is None:
            return
        task_id = f"{ctx.job_name}:{ctx.task_index}"
        try:
            ctx.rpc_client.call(
                "publish_ports", task_id=task_id,
                ports={"serve_port": port, "metrics_port": port})
        except Exception as e:
            # the replica still serves; only discovery via the driver is
            # degraded — callers with a static endpoint list are unaffected
            log.warning("could not publish service ports: %s", e)

    def run(self, ctx: TaskContext) -> int:
        conf = ctx.conf
        interval_s = (conf.get_int(keys.SERVING_HEALTHZ_INTERVAL_MS, 1000)
                      / 1000 if conf else 1.0)
        down_polls = max(1, conf.get_int(keys.SERVING_HEALTHZ_DOWN_POLLS, 3)
                         if conf else 3)
        ready_timeout_s = (conf.get_int(keys.SERVING_READY_TIMEOUT_MS,
                                        300000) / 1000 if conf else 300.0)
        contract_env = {**ctx.base_child_env, **self.build_env(ctx)}
        try:
            serve_port = int(contract_env.get(c.ENV_SERVE_PORT, "") or 0)
        except ValueError:
            serve_port = 0
        if serve_port <= 0:
            log.error("serving adapter needs %s (the executor's task "
                      "port) in the child env", c.ENV_SERVE_PORT)
            return 1
        from ..utils import containers

        if ctx.conf is not None and containers.container_enabled(ctx.conf):
            # loudly unsupported, not silently un-containerized: the
            # health-watch/port contract below assumes a host process
            log.error("tony.docker.enabled is not supported for the "
                      "serving job type yet; run replicas bare or use "
                      "the generic runtime")
            return 1
        # serving replicas deliberately do NOT adopt from the warm pool:
        # the provisioner's process-group SIGTERM is how a replica learns
        # to DRAIN (rolls, teardown — see _kill_tree's docstring), and an
        # adopted child lives in its own session where that signal never
        # arrives; its adopter-EOF watchdog would SIGKILL it mid-drain
        # instead, dropping in-flight requests on every roll. Until the
        # drain signal is relayed adoption-aware, replicas spawn cold.
        proc = subprocess.Popen(
            ["bash", "-c", ctx.command],
            env={**os.environ, **contract_env}, cwd=ctx.work_dir or None)
        ctx.child_process = proc
        ctx.note_span("child_spawned")

        ready = False
        down_streak = 0
        t0 = time.monotonic()
        while True:
            try:
                return proc.wait(timeout=interval_s)
            except subprocess.TimeoutExpired:
                pass
            state = self._poll_healthz(serve_port)
            if state == "ok":
                if not ready:
                    ready = True
                    ctx.note_span("serving_ready")
                    self._publish_ports(ctx, serve_port)
                    log.info("replica healthy on port %d after %.1fs",
                             serve_port, time.monotonic() - t0)
                down_streak = 0
            elif ready:
                # post-ready 503 = the serving loop's restart budget is
                # exhausted (or the server is draining toward exit); a
                # few unreachable polls = the HTTP server died under a
                # live process. Either way the replica is out of
                # rotation for good — hand the restart decision to the
                # driver's budget instead of hosting a zombie.
                down_streak += 1
                if down_streak >= down_polls:
                    log.error(
                        "replica /healthz %s for %d consecutive polls; "
                        "killing child for a budgeted driver restart",
                        state, down_streak)
                    _kill_tree(proc)
                    proc.wait(timeout=10)
                    return 1
            elif time.monotonic() - t0 > ready_timeout_s:
                log.error("replica never became healthy within %.0fs",
                          ready_timeout_s)
                _kill_tree(proc)
                proc.wait(timeout=10)
                return 1


class RouterTaskAdapter(ServingTaskAdapter):
    """Executor-side supervisor of one fleet-ROUTER child (``tony-tpu
    route``) — the ``router`` framework (docs/serving.md "Router tier
    HA"). The router tier rides the exact serving supervision shape:
    the child binds the task's published port (``TONY_SERVE_PORT``),
    the adapter watches its ``/healthz`` (FleetRouter.health: 503 on
    an empty fleet or a dead maintenance loop), the first healthy poll
    publishes ``serve_port``/``metrics_port`` (so the autoscaler's
    FleetWatcher — and an upstream LB reading get_task_infos — can
    find every front door), and a terminally-down router is killed
    into the per-task restart budget exactly like a replica. The only
    difference is the child env: routers take their flags from the
    role command itself, so none of the ``tony.serving.*`` serve-flag
    templating applies."""

    def build_env(self, ctx: TaskContext) -> dict[str, str]:
        import json

        return {
            c.ENV_CLUSTER_SPEC: json.dumps(ctx.cluster_spec),
            c.ENV_SERVE_PORT: ctx.base_child_env.get(c.ENV_TASK_PORT, ""),
        }
