"""PyTorch runtime adapter: c10d TCP rendezvous env.

Mirrors PyTorchRuntime.java:44-56 + Utils.parseClusterSpecForPytorch
(util/Utils.java:606-616): worker 0 is the rendezvous host; every task gets
INIT_METHOD=tcp://<worker0>, RANK, WORLD. Gradient allreduce stays inside
torch.distributed (Gloo on CPU hosts — NCCL has no TPU role).
"""

from __future__ import annotations

from .base import TaskContext
from .generic import GenericDriverAdapter, GenericTaskAdapter


class PyTorchDriverAdapter(GenericDriverAdapter):
    pass


class PyTorchTaskAdapter(GenericTaskAdapter):
    def build_env(self, ctx: TaskContext) -> dict[str, str]:
        env = super().build_env(ctx)
        workers = ctx.cluster_spec.get("worker", [])
        if not workers:
            raise RuntimeError("pytorch runtime requires a 'worker' role")
        env["INIT_METHOD"] = f"tcp://{workers[0]}"
        env["RANK"] = str(ctx.global_rank())
        env["WORLD"] = str(ctx.world_size())
        # torchrun-style aliases for modern scripts
        master_host, master_port = workers[0].rsplit(":", 1)
        env["MASTER_ADDR"] = master_host
        env["MASTER_PORT"] = master_port
        env["WORLD_SIZE"] = env["WORLD"]
        return env
