"""The job driver: session owner, capacity requester, liveness monitor.

Mirrors the reference ApplicationMaster (tony-core/.../ApplicationMaster.java):
lifecycle init -> prepare -> start -> monitor -> (reset/retry) -> stop
(:326-437), an RPC server for client+executors (:858-974), heartbeat liveness
(:201-221, onTaskDeemedDead:1229-1236), container-completion handling
(processFinishedContainer:1238-1274), registration-timeout and
startup-failure detection (:1276-1334), and whole-job retry that rebuilds the
session with session_id+1 (reset:611-627).

Capacity comes from a Provisioner instead of YARN; the "container" is an
executor process on a (TPU) host.
"""

from __future__ import annotations

import argparse
import logging
import os
import statistics
import sys
import threading
import time
from pathlib import Path
from typing import Any

from . import constants as c
from .api import DistributedMode, JobStatus, TaskStatus, now_ms
from .cluster import ContainerHandle, Provisioner, create_provisioner
from .conf import RoleSpec, TonyConf, keys
from .events import EventHandler
from .events.trace import TASK_TRACE_FILE, TraceWriter
from .events.types import (
    application_finished,
    application_inited,
    task_finished,
    task_started,
    task_trace,
)
from .events.driver_journal import DriverJournal, DriverState, load_state
from .metrics import (
    DRIVER_AUTOSCALE_QUEUE_DEPTH,
    DRIVER_AUTOSCALE_REPLICAS,
    DRIVER_AUTOSCALE_SCALE_DOWNS_TOTAL,
    DRIVER_AUTOSCALE_SCALE_UPS_TOTAL,
    DRIVER_AUTOSCALE_SCRAPE_FAILURES_TOTAL,
    DRIVER_AUTOSCALE_TTFT_P99_S,
    DRIVER_METRICSHUB_SCRAPES_TOTAL,
    DRIVER_METRICSHUB_SERIES,
    DRIVER_METRICSHUB_TARGETS,
    DRIVER_CHECKPOINT_AGE_S,
    DRIVER_GANG_LAUNCH_SECONDS,
    DRIVER_GANG_RESIZES_TOTAL,
    DRIVER_HEARTBEAT_EXPIRED_TOTAL,
    DRIVER_HEARTBEAT_INTERVAL_SECONDS,
    DRIVER_PREEMPTIONS_TOTAL,
    DRIVER_QUOTA_DONATIONS_TOTAL,
    DRIVER_QUOTA_POOL_FREE,
    DRIVER_QUOTA_POOL_SLOTS,
    DRIVER_QUOTA_RECLAIMS_TOTAL,
    DRIVER_QUOTA_SLOTS,
    DRIVER_RECOVERIES_TOTAL,
    DRIVER_STRAGGLER_HEARTBEAT_S,
    DRIVER_STRAGGLER_REGISTRATION_S,
    DRIVER_TASK_METRIC,
    DRIVER_TASK_RESTARTS_TOTAL,
    DRIVER_TASK_ROLLS_TOTAL,
    DRIVER_TASK_SERVICE_PORT,
    DRIVER_TASKS,
    DRIVER_TASKS_READOPTED_TOTAL,
    DRIVER_WARM_POOL_ADOPTIONS_TOTAL,
    DRIVER_WARM_POOL_MISSES_TOTAL,
    DRIVER_WARM_POOL_SIZE,
)
from .observability import PROM_CONTENT_TYPE, Histogram, PromRenderer, TaskTrace
from .rpc import RpcServer
from .scheduler import TaskScheduler
from .session import Session

log = logging.getLogger(__name__)


def _handle_pid(handle: ContainerHandle) -> int:
    """The executor pid behind a container handle (0 = unknown): a
    spawned handle's Popen pid, or a re-adopted handle's journaled pid."""
    if handle.process is not None:
        return handle.process.pid
    pid = handle.extra.get("pid", 0)
    return pid if isinstance(pid, int) else 0


def _lag_stats(rel: list[float]) -> dict[str, float]:
    """max/median of per-task lag values — the straggler gauge's two
    stats. An empty list (nothing registered / beating yet) reads as
    zero skew rather than omitting the series."""
    if not rel:
        return {"max": 0.0, "median": 0.0}
    return {"max": max(rel), "median": float(statistics.median(rel))}


class DriverService:
    """RPC-facing service — reference RpcForClient (ApplicationMaster.java:858-974).
    Public methods = wire methods."""

    def __init__(self, driver: "Driver"):
        self._d = driver

    # ------------------------------------------------------------- executors
    def register_worker(self, task_id: str, host: str, port: int,
                        attempt: int = -1):
        d = self._d
        # attempt fence: a superseded attempt's zombie executor (orphaned
        # across a driver recovery, or lingering past its SIGTERM grace)
        # must not register itself over the replacement the current
        # driver launched. ``attempt`` echoes the launch env's
        # TONY_TASK_ATTEMPT; -1 (absent) skips the fence for executors
        # that predate it.
        if attempt >= 0:
            current = d._attempts.get(task_id)
            if current is not None and attempt != current:
                raise ValueError(
                    f"stale attempt {attempt} of {task_id}: the current "
                    f"attempt is {current} (zombie registration refused)")
        task = d.session.register_task(task_id, host, port)
        if task is None:
            raise ValueError(f"unknown task {task_id}")
        d._jrec("register", task=task_id, host=host, port=port)
        d.heartbeats[task_id] = time.time()
        d._on_task_registered(task_id)
        log.info("registered %s at %s:%s (%d/%d)", task_id, host, port,
                 d.session.registered_count(), len(d.session.all_tasks()))
        # fault injection: kill listed tasks once the chief registers
        # (reference TEST_WORKER_TERMINATION, ApplicationMaster.java:1338-1349)
        victims = os.environ.get(c.TEST_WORKER_TERMINATION, "")
        if victims and d.session.is_chief(task.name, task.index):
            def _terminate():
                for victim in victims.split(","):
                    log.warning("fault injection: terminating %s", victim)
                    d._kill_task(victim.strip())
            threading.Thread(target=_terminate, daemon=True).start()
        return self.get_cluster_spec(task_id)

    def get_cluster_spec(self, task_id: str):
        """None until the runtime's gang barrier opens — the executor polls
        (reference 'return null until ready', TaskExecutor.java:296-298)."""
        d = self._d
        if not d.runtime_driver.can_start_task(d.mode, task_id):
            return None
        payload = d.runtime_driver.cluster_spec_payload(task_id)
        d._mark_running(task_id)    # the gang barrier opened for this task
        return payload

    def taskExecutorHeartbeat(self, task_id: str):  # wire name kept short below
        return self.heartbeat(task_id)

    def heartbeat(self, task_id: str):
        """Returns True, or — when a command is pending for this task — a
        one-shot dict: ``{"profile": {...}}`` (on-demand capture) and/or
        ``{"preempt": {...}}`` (drain notice: checkpoint at the next step
        boundary and exit). The heartbeat is the only driver->executor
        channel that already exists at steady state, so commands
        piggyback on its response (the executor's Heartbeater relays
        them; see Driver.request_profile / Driver.preempt_task)."""
        d = self._d
        if d._chaos_hb_drop and d._chaos_rng.random() < d._chaos_hb_drop:
            # fault injection: the beat is lost in transit — the caller
            # sees an RPC error and counts a miss, the driver records
            # nothing (a dropped packet updates no one's clock)
            raise RuntimeError("chaos: heartbeat dropped")
        prev = d.heartbeats.get(task_id)
        now = time.time()
        d.heartbeats[task_id] = now
        d._on_heartbeat(task_id, prev, now)
        cmd: dict[str, Any] = {}
        prof = d.take_profile_command(task_id)
        if prof:
            cmd["profile"] = prof
        pre = d.take_preempt_command(task_id)
        if pre:
            cmd["preempt"] = pre
        return cmd or True

    def register_execution_result(self, task_id: str, exit_code: int) -> str:
        log.info("%s reported exit code %d", task_id, exit_code)
        self._d.on_task_result(task_id, exit_code, source="executor")
        return "RECEIVED"

    def register_callback_info(self, task_id: str, payload: dict[str, Any]) -> bool:
        self._d.runtime_driver.receive_callback_info(task_id, payload)
        return True

    def publish_ports(self, task_id: str, ports: dict[str, int]) -> bool:
        """A task advertises named service ports (``serve_port``,
        ``metrics_port``, ...) — the generalization of the reference's
        TF_CONFIG endpoint plumbing. They land on the task's Session
        entry, ride the cluster-spec payload (``service_ports``),
        surface on get_task_infos for clients/routers, and render as
        ``driver_task_service_port`` gauges on the driver /metrics."""
        return self._d.publish_task_ports(task_id, ports)

    def roll_task(self, task_id: str) -> bool:
        """Rolling restart of one RUNNING task (client-privileged when
        token auth is on): SIGTERM the container — a serving replica
        drains in-flight requests on it — and relaunch WITHOUT spending
        the task's restart budget (a deliberate roll is an operator
        action, not a failure). The serving fleet's weight-update
        procedure: roll replicas one at a time behind the router (docs/
        serving.md "Fleet serving")."""
        return self._d.roll_task(task_id)

    def preempt_task(self, task_id: str) -> bool:
        """Relay a preemption notice to one RUNNING task (client-
        privileged when token auth is on): the operator/cloud knows the
        task's capacity is about to be reclaimed. The notice rides the
        task's next heartbeat response, the executor drops the
        ``$TONY_STEP_LOG.preempt`` flag, the training child checkpoints
        at its next step boundary and exits, and the driver relaunches
        WITHOUT spending restart budget (trace mark ``preempted``). See
        docs/training-robustness.md."""
        return self._d.preempt_task(task_id)

    def notify_preemption(self, task_id: str) -> bool:
        """An executor reports that IT received the preemption signal
        (cloud SIGTERM to its host): the driver marks the task mid-
        preempt so the coming container exit relaunches budget-free —
        the executor-initiated half of the drain contract."""
        return self._d.note_preemption(task_id, source="executor")

    def register_tensorboard_url(self, url: str) -> bool:
        self._d.tensorboard_url = url
        log.info("tensorboard at %s", url)
        return True

    def update_metrics(self, task_id: str, metrics: list[dict[str, Any]],
                       spans: list | None = None) -> bool:
        """``spans`` (optional, [name, unix_ts] pairs) are executor-side
        lifecycle spans (work_dir_ready, child_spawned, child_exited)
        merged into the task's trace — see Driver._merge_executor_spans."""
        self._d.metrics[task_id] = metrics
        if spans:
            self._d._merge_executor_spans(task_id, spans)
        return True

    def get_metrics(self, task_id: str):
        return self._d.metrics.get(task_id, [])

    def request_task_profile(self, task_id: str,
                             seconds: float = 5.0) -> bool:
        """Queue an on-demand profiler capture for one training worker
        (client-privileged when token auth is on): the command rides the
        task's next heartbeat response, the executor drops the
        ``$TONY_STEP_LOG.profile`` flag file, and the training child's
        StepTimer captures a jax.profiler trace at its next record
        boundary. See docs/observability.md "Device timing &
        profiling"."""
        return self._d.request_profile(task_id, seconds)

    # ---------------------------------------------------------------- client
    def get_task_infos(self):
        return [t.to_dict() for t in self._d.session.task_infos()]

    def get_application_state(self):
        d = self._d
        status = d.session.status
        # a failure before the driver finalizes is not terminal for the client
        # — the reference client polls through AM attempts (the app report
        # stays RUNNING until the last attempt gives up). run() flips
        # `finalized` before returning, so gating on it alone is race-free
        # even in the window between the last attempt's failure and its reset.
        if status == JobStatus.FAILED and not d.finalized:
            status = JobStatus.RUNNING
        return {
            "app_id": d.app_id,
            "status": status.value,
            "message": d.session.failure_message,
            "session_id": d.session.session_id,
            "tensorboard_url": d.tensorboard_url,
        }

    def finish_application(self) -> bool:
        """Client acknowledges the terminal state so the driver may exit
        (reference signalAMToFinish / FinishApplication RPC)."""
        self._d.client_signal.set()
        return True


class Driver:
    def __init__(
        self,
        conf: TonyConf,
        app_id: str,
        job_dir: str,
        token: str = "",
        user: str = "",
        provisioner: Provisioner | None = None,
        rpc_port: int = 0,
    ):
        self.conf = conf
        self.app_id = app_id
        self.job_dir = Path(job_dir)
        self.token = token
        self.user = user or os.environ.get("USER", "")
        self.tensorboard_url = ""
        self.metrics: dict[str, list[dict[str, Any]]] = {}
        self.heartbeats: dict[str, float] = {}
        self.client_signal = threading.Event()
        self.finalized = False
        self._stop_requested = threading.Event()
        self.mode = DistributedMode(
            str(conf.get(keys.APPLICATION_DISTRIBUTED_MODE, "GANG")).upper()
        )

        from .runtimes import get_runtime

        self._runtime = get_runtime(str(conf.get(keys.APPLICATION_FRAMEWORK, "jax")))
        self.runtime_driver = self._runtime.driver_adapter()
        # runtime may inject roles (horovod driver) before the session exists
        self.runtime_driver.validate_and_update_config(conf)
        conf.validate()

        self.provisioner = provisioner or create_provisioner(conf)
        self.provisioner.on_completion = self._on_container_completed

        self.session = Session(conf, session_id=0)
        self.runtime_driver.set_session(self.session)
        self.scheduler: TaskScheduler | None = None

        # per-principal auth: the root job secret (held by client + driver
        # only) derives one key per role; executors get ONLY the executor
        # key, so they cannot sign client-privileged calls. finish_application
        # flips the driver into teardown — an executor must not be able to
        # end the job for everyone (reference TonyPolicyProvider ACL split,
        # ApplicationMaster.java:483-503).
        from .rpc.protocol import derive_role_key

        self.executor_token = derive_role_key(token, "executor")
        roles = acl = None
        if token:
            roles = {
                "client": derive_role_key(token, "client"),
                "executor": self.executor_token,
            }
            # profile/roll/preempt commands are operator actions, like
            # ending the job: an executor key must not be able to aim
            # the profiler at — or restart/drain — its peers.
            # notify_preemption stays executor-callable: it only declares
            # the CALLER's own fate (the wire method rejects nothing an
            # executor couldn't do by exiting EXIT_PREEMPTED anyway)
            acl = {"finish_application": {"client"},
                   "request_task_profile": {"client"},
                   "roll_task": {"client"},
                   "preempt_task": {"client"}}
        rpc_host = str(conf.get(keys.AM_RPC_HOST, "127.0.0.1"))
        try:
            # recovery asks for the journaled port back so clients that
            # cached the old endpoint reconnect without re-resolving;
            # executors re-resolve driver.json either way
            self.rpc_server = RpcServer(
                host=rpc_host, port=rpc_port, token=token,
                roles=roles, acl=acl,
            )
        except OSError as e:
            if rpc_port == 0:
                raise
            log.warning("could not rebind recovered RPC port %d (%s); "
                        "taking an ephemeral port — executors re-resolve "
                        "driver.json", rpc_port, e)
            self.rpc_server = RpcServer(
                host=rpc_host, port=0, token=token, roles=roles, acl=acl,
            )
        self.rpc_server.register_service(DriverService(self))
        self.events: EventHandler | None = None
        self._handles: dict[str, ContainerHandle] = {}  # task_id -> handle
        self._launch_ms: dict[str, int] = {}            # task_id -> launch time
        self._restarts: dict[str, int] = {}             # task_id -> restarts used
        # ---- control-plane journal + recovery (events/driver_journal.py,
        # docs/training-robustness.md "Control-plane recovery") ----
        # per-task launch ordinal (monotonic across budget-free relaunches
        # too, unlike _restarts): echoed back on register_worker so a
        # superseded attempt's zombie executor is refused by the fence.
        # driver_generation counts this job's driver incarnations; a
        # recovered driver bumps it, rewrites driver.json with it, and
        # stamps it into every relaunch env.
        self._attempts: dict[str, int] = {}
        self._journal: DriverJournal | None = None
        self._recovered_state: DriverState | None = None
        self.driver_generation = 0
        self._recoveries = 0            # driver_recoveries_total
        self._readopted = 0             # driver_tasks_readopted_total
        # serializes the restart/preempt/resize paths — container
        # completion (watcher threads), heartbeat expiry (monitor
        # thread), and elastic resize — so a crash that coincides with
        # heartbeat death can't double-spend the budget or kill the
        # replacement the other path just launched. Reentrant: a
        # completion handled under the lock may escalate into a resize
        # that takes it again.
        self._restart_lock = threading.RLock()
        self._retries_left = conf.get_int(keys.AM_RETRY_COUNT, 0)
        self._start_ms = now_ms()

        # ---- task lifecycle telemetry (observability.TaskTrace) ----
        # every task gets a host-monotonic span trace (requested ->
        # allocated -> launched -> registered -> first_heartbeat ->
        # running -> terminal) recorded here and enriched by executor-
        # side spans over update_metrics; sealed traces go to
        # tasks.trace.jsonl + a TASK_TRACE jhist event. One lock: marks
        # come from RPC threads, watcher threads, and the monitor loop.
        self._tt_lock = threading.Lock()
        self.task_traces: dict[str, TaskTrace] = {}   # live (unsealed)
        self._task_trace_writer: TraceWriter | None = None
        self._gang_hist: dict[str, Histogram] = {}    # role -> req->reg
        self._hb_hist = Histogram()                   # beat inter-arrival
        self._restart_count = 0                       # budget units spent
        self._hb_expired_count = 0                    # liveness expiries
        self._reg_t: dict[str, float] = {}            # task -> reg monotime
        self._barrier_open: set[str] = set()          # "running" marked
        self._first_beat: set[str] = set()            # "first_heartbeat"
        self._exec_spans_seen: dict[str, set] = {}    # per-attempt dedupe
        self._attempt_wall: dict[str, float] = {}     # restart wall fence
        self._metrics_httpd = None
        # pending on-demand profiler captures, task_id -> command dict;
        # queued by request_profile (client RPC or the metrics server's
        # /profile route), drained one-shot by the task's next heartbeat
        self._profile_cmds: dict[str, dict] = {}
        self._profile_lock = threading.Lock()
        # tasks mid-roll (roll_task RPC): their next container completion
        # relaunches WITHOUT charging the restart budget. One completion
        # per container, so plain set semantics suffice.
        self._rolls: set[str] = set()
        self._roll_count = 0
        # ---- warm executor pool (tony_tpu/warmpool.py) ----
        # pool-aware relaunch: EVERY launch path — first launch, budgeted
        # restart, budget-free preempt/resize/roll relaunch — runs the
        # executor-side adoption (runtimes/base.spawn_or_adopt), so a
        # recovery skips the prepaid jax-import/backend/data bill. The
        # driver seeds the local pool at prepare() (standbys warm while
        # the first gang launches), counts adoptions/misses from the
        # merged child_adopted/child_spawned spans, and reaps the pool at
        # stop() so teardown never orphans a standby.
        from .warmpool import WarmPool

        # standbys warm under the same execution env the task children
        # get (_task_env applies the same pairs), so the env fingerprint
        # matches at adoption
        self._warm_pool = WarmPool.from_conf(
            conf, str(self.job_dir), spawn_env=self._execution_env())
        self._warm_adoptions = 0
        self._warm_misses = 0
        # ---- elastic, preemption-tolerant training state ----
        # (docs/training-robustness.md). Tasks mid-preemption-drain: the
        # driver relayed (or was told of) a "preempting" notice; the
        # container's exit relaunches budget-free, trace-marked
        # 'preempted'. Same ledger discipline as rolls.
        self._preempts: set[str] = set()
        self._preempt_count = 0
        self._preempt_cmds: set[str] = set()     # pending heartbeat relays
        # survivors mid-resize-drain: their exits relaunch budget-free
        # into the new gang generation
        self._resizes: set[str] = set()
        self._resize_count = 0
        self._detach_t: dict[str, float] = {}    # task -> detach monotime
        # stops the DRIVER itself initiated (fault-injection kill,
        # heartbeat-expiry stop, straggler stop): the dying executor's
        # SIGTERM handler will dutifully report a "preemption", and
        # honoring it would relabel a deliberate kill as budget-free.
        # Cleared when the task's next attempt launches.
        self._driver_stops: set[str] = set()
        self._elastic = conf.get_bool(keys.TRAIN_ELASTIC_ENABLED, False)
        self._elastic_min = conf.get_int(keys.TRAIN_ELASTIC_MIN_INSTANCES, 1)
        self._rescale_retry_s = conf.get_int(
            keys.TRAIN_RESCALE_RETRY_MS, 30000) / 1000
        # straggler action: consecutive slow strikes per task, plus a
        # once-per-condition log guard for budgetless stragglers
        self._straggler_factor = float(
            conf.get(keys.TRAIN_STRAGGLER_RESTART_FACTOR, 0) or 0)
        self._straggler_grace = max(
            1, conf.get_int(keys.TRAIN_STRAGGLER_GRACE_CHECKS, 3))
        self._straggler_strikes: dict[str, int] = {}
        self._straggler_check_t = 0.0
        # ---- closed-loop autoscaler + multi-tenant arbiter ----
        # (tony_tpu/autoscale.py, docs/autoscaling.md). Ledger
        # discipline mirrors rolls/preempts/resizes: _parked = slots
        # the autoscaler holds detached (only a scale-up relaunches
        # them — the elastic rescale timer must skip them);
        # _scale_downs = replicas mid-scale-down drain (their
        # completion PARKS the slot instead of relaunching);
        # _donations = batch workers mid-donation drain (their
        # completion detaches the slot, freeing pool capacity for the
        # interactive tier); _donated = donated slots awaiting reclaim
        # (the rescale timer re-attaches them only once the arbiter
        # has free capacity again).
        from .autoscale import ResourceArbiter

        self._autoscale_enabled = conf.get_bool(keys.AUTOSCALE_ENABLED,
                                                False)
        roles_sorted = sorted(self.session.role_specs)
        self._autoscale_role = str(
            conf.get(keys.AUTOSCALE_ROLE, "") or "") or (
            roles_sorted[0] if len(roles_sorted) == 1 else "")
        # the router TIER's role (docs/serving.md "Router tier HA"):
        # explicit conf, else the first role whose framework resolves
        # to "router" — the same per-role-override-then-app-level
        # resolution the executor applies
        self._router_role = str(
            conf.get(keys.AUTOSCALE_ROUTER_ROLE, "") or "")
        if not self._router_role:
            for rname in roles_sorted:
                fw = str(
                    conf.get(keys.role_key(rname, "framework"), "")
                    or conf.get(keys.APPLICATION_FRAMEWORK, "jax"))
                if fw == "router":
                    self._router_role = rname
                    break
        self.arbiter = ResourceArbiter(
            self.session,
            pool_slots=conf.get_int(keys.QUOTA_POOL_SLOTS, 0))
        self._parked: set[str] = set()
        self._scale_downs: set[str] = set()
        self._donations: dict[str, str] = {}
        # donor -> the SLO breach that motivated the donation (transient
        # display state; a recovered driver falls back to a synthesized
        # reason when the discharge lands post-recovery)
        self._donation_reasons: dict[str, str] = {}
        self._donated: set[str] = set()
        self._scale_up_count = 0
        self._scale_down_count = 0
        # the router-TIER slices of the two counters above, rendered as
        # the {tier="router"} series next to the unlabeled totals
        self._router_scale_up_count = 0
        self._router_scale_down_count = 0
        self._autoscale_runner = None
        self._controller = None
        self._recovered_scale_t: float | None = None
        # fleet metrics pipeline + SLO engine (tony_tpu/metricshub.py,
        # tony_tpu/slo.py) — built in _start_metricshub() during
        # prepare(); None when neither autoscaling nor SLOs are on
        self._metrics_hub = None
        self._slo_engine = None
        if self._autoscale_enabled and self._autoscale_role:
            spec = self.session.role_specs.get(self._autoscale_role)
            n_min = max(0, conf.get_int(keys.AUTOSCALE_MIN, 1))
            if spec is not None and n_min < spec.instances:
                # slots above the floor start PARKED: detached (never
                # launched, invisible to barrier/completion policy)
                # until a scale-up decision claims one. Recovery
                # overwrites this from the journal (restore_formation
                # replaces the detached set wholesale).
                for task in self.session.tasks.get(self._autoscale_role,
                                                   []):
                    if task.index >= n_min:
                        self.session.detach_task(task.task_id)
                        self._parked.add(task.task_id)
        if (self._autoscale_enabled and self._router_role
                and float(conf.get(keys.AUTOSCALE_ROUTER_RELAY_SLO, 0)
                          or 0) > 0):
            # router-tier headroom parks the same way: front doors
            # above the router floor start detached until the router
            # law claims one
            r_min = max(0, conf.get_int(keys.AUTOSCALE_ROUTER_MIN, 1))
            for task in self.session.tasks.get(self._router_role, []):
                if task.index >= r_min:
                    self.session.detach_task(task.task_id)
                    self._parked.add(task.task_id)
        # seeded driver chaos (TONY_TEST_DRIVER_*, constants.py) — the
        # cluster-side mirror of the serving chaos knobs; read once so a
        # run's fault sequence is reproducible from the seed
        import random as _random

        def _rate(name):
            try:
                return min(1.0, max(0.0, float(os.environ.get(name, "0"))))
            except ValueError:
                log.error("bad %s value; chaos knob disabled", name)
                return 0.0

        self._chaos_kill_rate = _rate(c.TEST_DRIVER_KILL_RATE)
        self._chaos_hb_drop = _rate(c.TEST_DRIVER_HEARTBEAT_DROP_RATE)

        def _at_step(name):
            try:
                return int(os.environ.get(name, "0"))
            except ValueError:
                log.error("bad %s value; chaos knob disabled", name)
                return 0

        self._chaos_preempt_at = _at_step(c.TEST_DRIVER_PREEMPT_AT_STEP)
        self._chaos_preempt_fired = False
        # driver suicide keyed off the gang's pushed train step — the
        # control-plane death injection behind bench.py --driver-failover
        self._chaos_sigkill_at = _at_step(c.TEST_DRIVER_SIGKILL_AT_STEP)
        self._chaos_sigkill_fired = False
        self._chaos_rng = _random.Random(
            int(os.environ.get(c.TEST_DRIVER_CHAOS_SEED, "0") or 0))
        if (self._chaos_kill_rate or self._chaos_hb_drop
                or self._chaos_preempt_at or self._chaos_sigkill_at):
            log.warning(
                "driver chaos armed: kill_rate=%s hb_drop=%s "
                "preempt_at_step=%s sigkill_at_step=%s",
                self._chaos_kill_rate, self._chaos_hb_drop,
                self._chaos_preempt_at, self._chaos_sigkill_at)
        # compile visibility for code running IN the driver process
        # (enable-preprocess / notebook jobs): the driver's /metrics
        # carries its own compile histogram next to the compile totals
        # training children push as task metrics. only_if_loaded: the
        # orchestration-only driver must not pay a full jax import for
        # this — if jax is absent no compile could have fired, and
        # render_metrics() re-tries the install once user code brought
        # jax in.
        from .observability import install_compile_telemetry

        self._compile_telemetry = install_compile_telemetry(
            only_if_loaded=True)

    # ------------------------------------------------------------- lifecycle
    def run(self) -> JobStatus:
        self.prepare()
        try:
            while True:
                self.start_session()
                status = self.monitor()
                if status == JobStatus.FAILED and self._retries_left > 0:
                    self._retries_left -= 1
                    log.warning(
                        "job failed (%s); retrying (%d attempts left)",
                        self.session.failure_message, self._retries_left,
                    )
                    self.reset()
                    continue
                self.finalized = True
                return status
        finally:
            # also reached via exceptions out of start_session/monitor/reset:
            # the state the client reads must go terminal either way
            self.finalized = True
            self.stop()

    def prepare(self) -> None:
        """RPC up, events up, endpoint advertised — reference prepare:442-526."""
        self.rpc_server.start()
        hist_inter = str(self.conf.get(keys.HISTORY_INTERMEDIATE))
        self.events = EventHandler(hist_inter, self.app_id, user=self.user)
        self.events.start()
        self.events.emit(
            application_inited(
                self.app_id, len(self.session.all_tasks()), self.rpc_server.address[0]
            )
        )
        self.conf.write_final(self.job_dir)
        # Advertise the RPC endpoint for the client (plays the role of the
        # YARN application report carrying the AM host:port).
        import json

        info = {"host": self.rpc_server.address[0], "port": self.rpc_server.port,
                "app_id": self.app_id, "pid": os.getpid(),
                # consumers (executors riding an outage, warm-pool
                # standbys, router discovery) use the generation bump to
                # tell "the same driver" from "its recovered successor"
                "driver_generation": self.driver_generation}
        self._task_trace_writer = TraceWriter(
            self.events.job_dir, filename=TASK_TRACE_FILE)
        self._start_metrics_server()
        if self.metrics_port is not None:
            info["metrics_port"] = self.metrics_port
        tmp = self.job_dir / (c.DRIVER_INFO_FILE + ".tmp")
        tmp.write_text(json.dumps(info))
        tmp.rename(self.job_dir / c.DRIVER_INFO_FILE)
        # control-plane journal: opened append (recovery compacted it
        # before construction), meta re-stamped last-wins so the journal
        # always names the CURRENT endpoint + generation
        self._journal = DriverJournal(self.job_dir / c.DRIVER_JOURNAL_FILE)
        self._jrec("meta", app_id=self.app_id, token=self.token,
                   session_id=self.session.session_id,
                   rpc_port=self.rpc_server.port,
                   driver_generation=self.driver_generation)
        if self._recovered_state is not None:
            self._jrec("recovered",
                       driver_generation=self.driver_generation,
                       t=time.time())
        elif self._parked:
            # fresh job: the pre-parked autoscale slots must be
            # recoverable facts, not re-derived config (a recovered
            # driver replays detached+parked wholesale)
            for task_id in sorted(self._parked):
                self._jrec("detach", task=task_id)
                self._jrec("park", task=task_id)
        self._start_metricshub()
        self._start_autoscaler()
        # seed the warm pool on THIS host for local capacity: standbys
        # prepay the jax/backend bill while the first gang launches, so
        # even the first relaunch adopts. Remote hosts seed their own
        # pools (each executor tops up its host's pool at startup).
        from .cluster.provisioner import LocalProvisioner

        if (self._warm_pool is not None
                and isinstance(self.provisioner, LocalProvisioner)):
            try:
                # per-job pools bind their standbys to this driver's pid
                # (orphan self-reaping if the driver dies without stop());
                # an explicit host-level pool outlives jobs by design
                if Path(self._warm_pool.dir).resolve().is_relative_to(
                        self.job_dir.resolve()):
                    self._warm_pool.watch_pid = os.getpid()
                n = self._warm_pool.ensure()
                if n:
                    log.info("seeded warm pool with %d standby(s) in %s",
                             n, self._warm_pool.dir)
            except Exception:
                log.exception("warm pool seeding failed; launches stay cold")

    def start_session(self) -> None:
        """Build scheduler and request capacity — reference start:577-608.
        With enable-preprocess and a single-instance job, the driver runs the
        command itself instead of launching a container (reference
        doPreprocessingJob:784-836, the notebook/preprocess path)."""
        if self.conf.get_bool(keys.APPLICATION_ENABLE_PREPROCESS, False):
            specs = list(self.session.role_specs.values())
            if len(specs) == 1 and specs[0].instances == 1:
                threading.Thread(
                    target=self._run_in_driver, args=(specs[0],), daemon=True
                ).start()
                return
            log.warning("enable-preprocess needs a single-instance job; "
                        "falling back to container launch")
        self.scheduler = TaskScheduler(
            self.conf, list(self.session.role_specs.values()), self._request_role
        )
        if self._recovered_state is not None:
            # roles the dead driver already launched must not be
            # re-requested wholesale (their tasks were re-adopted or are
            # being relaunched one at a time through the expiry path);
            # journaled completions replay into the DAG so dependents of
            # finished roles still get scheduled
            launched = {tid.partition(":")[0]
                        for tid, rec in self._recovered_state.tasks.items()
                        if rec.attempt > 0}
            self.scheduler.restore(launched)
            for tid, rec in self._recovered_state.tasks.items():
                if rec.terminal:
                    self.scheduler.on_task_completed(
                        tid.partition(":")[0], rec.exit_code == 0)
            # a role can be PARTIALLY launched (the driver died inside
            # _request_role): its journaled tasks were restored, but a
            # never-journaled sibling has no liveness entry, no
            # registration-timeout entry, and — with the role marked
            # scheduled — no request coming either. Launch the missing
            # instances individually or the gang barrier waits forever.
            for role in sorted(launched):
                spec = self.session.role_specs.get(role)
                if spec is None:
                    continue
                for task in self.session.tasks.get(role, []):
                    rec = self._recovered_state.tasks.get(task.task_id)
                    if ((rec is None or rec.attempt == 0)
                            and not task.status.is_terminal()):
                        log.warning(
                            "recovery: %s of partially-launched role %s "
                            "was never launched by the dead driver; "
                            "launching it now", task.task_id, role)
                        self._relaunch_task(task.task_id, spec, task.index)
        self.scheduler.schedule()

    def _run_in_driver(self, spec: RoleSpec) -> None:
        import subprocess

        task = self.session.get_task(spec.name, 0)
        self._trace_mark(task.task_id, "requested", role=spec.name)
        self._trace_mark(task.task_id, "launched")
        self.session.register_task(task.task_id, self.rpc_server.address[0], -1)
        self._on_task_registered(task.task_id)
        if self.events:
            self.events.emit(task_started(task.task_id, self.rpc_server.address[0]))
        env = {**os.environ, **self._task_env(spec, 0)}
        log_dir = self.job_dir / "logs"
        log_dir.mkdir(parents=True, exist_ok=True)
        with open(log_dir / f"{spec.name}_0.stdout", "ab") as out:
            proc = subprocess.Popen(
                ["bash", "-c", spec.command], env=env,
                stdout=out, stderr=subprocess.STDOUT,
            )
            code = proc.wait()
        self.on_task_result(task.task_id, code, source="driver")

    def _request_role(self, spec: RoleSpec) -> None:
        """Launch all instances of a role — the local/TPU analogue of
        addContainerRequest + ContainerLauncher (allocation is immediate for
        a provisioner that owns its capacity; a TPU slice is gang-allocated)."""
        from .runtimes.generic import GenericDriverAdapter

        if isinstance(self.runtime_driver, GenericDriverAdapter):
            self.runtime_driver.note_requests_submitted()
        hold = os.environ.get(c.TEST_ALLOCATION_HOLD, "")
        for index in range(spec.instances):
            task = self.session.get_task(spec.name, index)
            if task is None or task.status.is_terminal():
                continue
            if task.task_id in self.session.detached:
                # a PARKED autoscale slot (or a journaled detach): only
                # a scale-up decision / capacity return launches it
                continue
            task.status = TaskStatus.REQUESTED
            self._trace_mark(task.task_id, "requested", role=spec.name)
            if hold == f"{spec.name}#{index}":
                # fault hook: this task never receives capacity (gang
                # deadlock — broken by the allocation-timeout health check)
                log.info("TEST_ALLOCATION_HOLD: withholding capacity for %s",
                         task.task_id)
                continue
            env = self._task_env(spec, index)
            env[c.ENV_TASK_ATTEMPT] = str(
                self._bump_attempt(task.task_id))
            # launch + handle publication are atomic vs the completion
            # callback (which takes the same lock): a container that
            # exits faster than this thread stores its handle would
            # otherwise read as "superseded" and its completion would be
            # silently dropped, orphaning the task. The ALLOCATED
            # transition is upgrade-only for the sibling race (a fast
            # executor REGISTERING before this bookkeeping finishes must
            # not be stomped back from RUNNING).
            with self._restart_lock:
                handle = self.provisioner.launch(
                    spec, index, env, self.job_dir / "logs"
                )
                self._handles[task.task_id] = handle
            self.session.note_allocated(task.task_id, handle.container_id)
            self._journal_launch(task.task_id, handle)
            self._trace_mark(task.task_id, "allocated", host=handle.host)
            task.host = handle.host
            # per-task log URL, surfaced to the client and portal (reference
            # prints each container's log URL, util/Utils.java:220-235). The
            # provisioner that opened the file owns the path; fall back to
            # the conventional location for provisioners that don't report it
            task.url = handle.extra.get("log_path") or str(
                self.job_dir / "logs" / f"{spec.name}_{index}.stdout"
            )
            self._launch_ms[task.task_id] = now_ms()
            self._trace_mark(task.task_id, "launched")
            if self.events:
                self.events.emit(
                    task_started(task.task_id, handle.host, url=task.url)
                )
            log.info("launched %s as %s on %s", task.task_id,
                     handle.container_id, handle.host)

    def _task_env(self, spec: RoleSpec, index: int) -> dict[str, str]:
        """The driver->executor env contract — reference ContainerLauncher
        env assembly (ApplicationMaster.java:1179-1192)."""
        env = {
            c.ENV_JOB_NAME: spec.name,
            c.ENV_TASK_INDEX: str(index),
            c.ENV_TASK_NUM: str(spec.instances),
            # ACTIVE complement: an elastically-resized gang launches its
            # attempts with the formation it is actually forming (the
            # authoritative world size still arrives with the cluster
            # spec at barrier time)
            c.ENV_NUM_TOTAL_TASKS: str(len(self.session.active_tasks())),
            c.ENV_GANG_GENERATION: str(self.session.gang_generation),
            c.ENV_DRIVER_GENERATION: str(self.driver_generation),
            c.ENV_IS_CHIEF: str(self.session.is_chief(spec.name, index)).lower(),
            c.ENV_SESSION_ID: str(self.session.session_id),
            c.ENV_DISTRIBUTED_MODE: self.mode.value,
            c.ENV_DRIVER_HOST: self.rpc_server.address[0],
            c.ENV_DRIVER_PORT: str(self.rpc_server.port),
            c.ENV_APP_ID: self.app_id,
            c.ENV_JOB_DIR: str(self.job_dir),
            c.ENV_TOKEN: self.executor_token,
            c.ENV_TASK_COMMAND: spec.command,
        }
        # job-archive shipping (reference HDFS localization seam): executors
        # on hosts without the staging FS fetch + unpack this URI
        archive_uri = str(self.conf.get(keys.APPLICATION_ARCHIVE_URI, "") or "")
        if archive_uri:
            env[c.ENV_JOB_ARCHIVE] = archive_uri
            # integrity digest rides the launch env, not the archive itself
            # (the hash cannot live inside the bytes it covers)
            digest = str(
                self.conf.get(keys.APPLICATION_ARCHIVE_SHA256, "") or ""
            )
            if digest:
                env[c.ENV_JOB_ARCHIVE_SHA256] = digest
        if self.conf.get_bool(keys.TASK_LOCALIZE, False):
            env[c.ENV_LOCALIZE] = "true"
        env.update(self._execution_env())
        env.update(spec.env)
        return env

    def _execution_env(self) -> dict[str, str]:
        """``tony.execution.env`` K=V pairs — ONE parse shared by the
        task launch env and the warm-pool standby spawn env, so standbys
        always warm under the env the children they'll adopt for get."""
        env: dict[str, str] = {}
        for kv in self.conf.get_list(keys.EXECUTION_ENV):
            if "=" in kv:
                k, v = kv.split("=", 1)
                env[k] = v
        return env

    # -------------------------------------------------- control-plane journal
    def _jrec(self, op: str, **fields) -> None:
        """Best-effort journal append (no-op before prepare / after
        close): the journal must never be able to take the driver
        down."""
        if self._journal is not None:
            self._journal.record(op, **fields)

    # ------------------------------------------------------- task telemetry
    def _trace_mark(self, task_id: str, span: str, **attrs) -> None:
        """Record one lifecycle span on the task's trace (created on
        first mark). Host-monotonic, same clock contract as the serving
        traces (docs/observability.md)."""
        with self._tt_lock:
            tr = self.task_traces.get(task_id)
            if tr is None:
                tr = self.task_traces[task_id] = TaskTrace(task_id)
            tr.mark(span)
            if attrs:
                tr.attrs.update(attrs)

    def _on_task_registered(self, task_id: str) -> None:
        """Registration: mark the span and feed the per-role gang-launch
        histogram (capacity request -> registration, measured from the
        newest ``requested`` so restarts time their own attempt). Once
        per attempt: the RPC client retries transport errors, so a
        re-delivered register_worker must not double-count the histogram
        or duplicate the span."""
        role = task_id.partition(":")[0]
        with self._tt_lock:
            if task_id in self._reg_t:
                return
            tr = self.task_traces.get(task_id)
            if tr is None:
                tr = self.task_traces[task_id] = TaskTrace(task_id)
            t_req = tr.last_t("requested")
            tr.mark("registered")
            now = tr.spans[-1][1]
            self._reg_t[task_id] = now
            if t_req is not None:
                h = self._gang_hist.get(role)
                if h is None:
                    h = self._gang_hist[role] = Histogram()
                h.observe(max(0.0, now - t_req))

    def _on_heartbeat(self, task_id: str, prev: float | None,
                      now: float) -> None:
        with self._tt_lock:
            if prev is not None:
                self._hb_hist.observe(max(0.0, now - prev))
            # first_heartbeat only counts after registration: the
            # executor starts its heartbeater BEFORE registering, and a
            # beat racing ahead of register_worker must not put
            # first_heartbeat before 'registered' in the documented chain
            if task_id not in self._first_beat and task_id in self._reg_t:
                tr = self.task_traces.get(task_id)
                if tr is not None:
                    self._first_beat.add(task_id)
                    tr.mark("first_heartbeat")

    def _mark_running(self, task_id: str) -> None:
        """The gang barrier opened for this task (its cluster spec was
        handed out) — once per attempt."""
        with self._tt_lock:
            if task_id in self._barrier_open:
                return
            tr = self.task_traces.get(task_id)
            if tr is not None:
                self._barrier_open.add(task_id)
                tr.mark("running")

    def _merge_executor_spans(self, task_id: str, spans: list) -> None:
        """Executor-side lifecycle spans arrive as [name, unix_ts] pairs
        — optionally [name, unix_ts, attrs] (the warm-pool hit/miss
        marks carry a ``warm_pool`` attr) — the monitor pushes its
        cumulative list every interval; each name merges once per
        attempt, re-anchored from the executor's wall clock onto this
        host's monotonic timeline. Cross-host NTP skew can shift them
        against driver-observed spans but the driver's own span order is
        never affected; the waterfall sorts by timestamp for display.
        ``child_adopted`` / pool-missed ``child_spawned`` feed the
        driver_warm_pool_{adoptions,misses}_total counters and the
        task's wire-visible ``launch_path``."""
        offset = time.monotonic() - time.time()
        with self._tt_lock:
            tr = self.task_traces.get(task_id)
            if tr is None:
                return
            # a superseded attempt's executor can outlive its SIGTERM
            # grace window and keep pushing its cumulative span list;
            # merging those would both backdate the restarted chain and
            # mark the names seen, suppressing the NEW attempt's spans.
            # Spans stamped before this attempt began are the old
            # process talking (same NTP-skew caveat as the re-anchoring
            # above).
            floor = self._attempt_wall.get(task_id, 0.0)
            seen = self._exec_spans_seen.setdefault(task_id, set())
            for item in spans:
                try:
                    name, unix_t = item[0], float(item[1])
                except (TypeError, ValueError, IndexError):
                    continue        # malformed push must not kill the RPC
                if not isinstance(name, str) or name in seen:
                    continue
                if unix_t < floor:
                    continue
                seen.add(name)
                attrs = (item[2] if len(item) > 2
                         and isinstance(item[2], dict) else {})
                for k, v in attrs.items():
                    if isinstance(k, str) and isinstance(
                            v, (str, int, float, bool)):
                        tr.attrs[k] = v
                tr.mark(name, t=unix_t + offset)
                if name in ("child_adopted", "child_spawned"):
                    self._note_launch_path(
                        task_id, name, attrs.get("warm_pool"))

    def _note_launch_path(self, task_id: str, span: str,
                          warm_pool) -> None:
        """Warm-pool accounting off the merged launch span (caller holds
        _tt_lock; once per attempt via the span-dedupe set): adoption
        and configured-pool-miss counters plus the task's wire-visible
        launch_path ("adopted"/"cold" on TaskInfo)."""
        task = self.session.get_task_by_id(task_id)
        if span == "child_adopted":
            self._warm_adoptions += 1
            if task is not None:
                task.launch_path = "adopted"
        else:
            if warm_pool == "miss":
                self._warm_misses += 1
            if task is not None:
                task.launch_path = "cold"

    def _clear_attempt_state_locked(self, task_id: str) -> None:
        """Drop the once-per-attempt markers. Caller holds _tt_lock."""
        self._exec_spans_seen.pop(task_id, None)
        self._barrier_open.discard(task_id)
        self._first_beat.discard(task_id)
        self._reg_t.pop(task_id, None)
        self._attempt_wall.pop(task_id, None)

    def _clear_attempt_state(self, task_id: str) -> None:
        """Reset the once-per-attempt markers so a restarted task records
        a fresh registered/first_heartbeat/running/executor-span chain,
        and fence off the superseded attempt's late span pushes.
        Caller holds no locks."""
        with self._tt_lock:
            self._clear_attempt_state_locked(task_id)
            self._attempt_wall[task_id] = time.time()

    def _seal_task_trace(self, task_id: str, terminal: str,
                         **attrs) -> None:
        """Close the task's trace with its terminal span, append the
        record to tasks.trace.jsonl, and embed it in the jhist stream as
        a TASK_TRACE event. Idempotent: a second seal (completion racing
        heartbeat expiry) finds no live trace and is a no-op."""
        with self._tt_lock:
            tr = self.task_traces.pop(task_id, None)
            if tr is None:
                return
            self._clear_attempt_state_locked(task_id)
            if attrs:
                tr.attrs.update(attrs)
            tr.attrs.setdefault("restarts", self._restarts.get(task_id, 0))
            tr.mark(terminal)
            record = tr.to_dict()
        if self._task_trace_writer is not None:
            self._task_trace_writer.write(record)
        if self.events:
            self.events.emit(task_trace(record))

    def _seal_remaining_traces(self) -> None:
        """Seal every still-live trace by its task's final status — stop
        and whole-job retry must leave only terminal traces behind."""
        with self._tt_lock:
            live = list(self.task_traces)
        for task_id in live:
            task = self.session.get_task_by_id(task_id)
            status = task.status if task is not None else TaskStatus.KILLED
            terminal = {TaskStatus.SUCCEEDED: "finished",
                        TaskStatus.FAILED: "failed"}.get(status, "killed")
            self._seal_task_trace(task_id, terminal, status=status.value)

    # ------------------------------------------------------- driver /metrics
    def _start_metrics_server(self) -> None:
        """GET /metrics in Prometheus text 0.0.4 for the job driver —
        the cluster-side sibling of the serve endpoint (docs/
        observability.md "Driver metrics"). Port from
        ``tony.am.metrics-port`` (0 = ephemeral, advertised as
        ``metrics_port`` in driver.json; negative = disabled)."""
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        port = self.conf.get_int(keys.AM_METRICS_PORT, 0)
        if port < 0:
            return
        driver = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                log.debug("metrics: " + fmt, *args)

            def do_GET(self):
                route = self.path.partition("?")[0]
                if route == "/metrics":
                    try:
                        body = driver.render_metrics().encode()
                        code, ctype = 200, PROM_CONTENT_TYPE
                    except Exception as e:   # a scrape must never 500 silently
                        log.exception("metrics render failed")
                        body, code, ctype = (
                            f"error: {e}".encode(), 500, "text/plain")
                elif route == "/slo":
                    # the SLO engine's JSON snapshot (burn rates, alert
                    # state, budget accounting, transition history) —
                    # the `tony-tpu slo` CLI's and bench's read path
                    import json as _json

                    ctype = "application/json"
                    if driver._slo_engine is None:
                        body, code = _json.dumps(
                            {"error": "no SLOs declared "
                             "(tony.slo.<name>.objective)"}).encode(), 404
                    else:
                        try:
                            body = _json.dumps(
                                driver._slo_engine.snapshot()).encode()
                            code = 200
                        except Exception as e:
                            log.exception("slo snapshot failed")
                            body, code = _json.dumps(
                                {"error": str(e)}).encode(), 500
                elif route == "/profile":
                    # operator convenience trigger for the same command
                    # the client RPC queues: curl ':port/profile?task=
                    # worker:0&seconds=5'. Available ONLY when token auth
                    # is off (local dev): with auth on, this unauthed
                    # HTTP route would hand any network peer — or an
                    # executor child on the same host — the profiler
                    # action the RPC ACL restricts to the client key, and
                    # the metrics server binds the same possibly-routable
                    # host the RPC does.
                    import json as _json
                    from urllib.parse import parse_qs, urlparse

                    ctype = "application/json"
                    if driver.token:
                        body, code = _json.dumps(
                            {"error": "token auth is on: use the "
                             "client-authenticated request_task_profile "
                             "RPC"}).encode(), 403
                        self.send_response(code)
                        self.send_header("Content-Type", ctype)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                        return
                    qs = parse_qs(urlparse(self.path).query)
                    task_id = qs.get("task", [""])[0]
                    try:
                        ok = driver.request_profile(
                            task_id, float(qs.get("seconds", ["5"])[0]))
                        body = _json.dumps(
                            {"queued": ok, "task": task_id}).encode()
                        code = 200 if ok else 404
                    except (ValueError, TypeError) as e:
                        body, code = _json.dumps(
                            {"error": str(e)}).encode(), 400
                else:
                    body, code, ctype = b"not found", 404, "text/plain"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        host = str(self.conf.get(keys.AM_RPC_HOST, "127.0.0.1"))
        try:
            self._metrics_httpd = ThreadingHTTPServer((host, port), _Handler)
        except OSError as e:
            # a taken port must not fail the job — telemetry is optional
            log.error("could not bind driver metrics port %s: %s", port, e)
            return
        threading.Thread(target=self._metrics_httpd.serve_forever,
                         name="driver-metrics", daemon=True).start()

    @property
    def metrics_port(self) -> int | None:
        return (self._metrics_httpd.server_address[1]
                if self._metrics_httpd is not None else None)

    def render_metrics(self) -> str:
        """The driver /metrics payload: per-role gang-launch histograms,
        the heartbeat inter-arrival histogram, restart/expiry counters,
        task-state gauges, the per-role straggler gauges (max/median
        registration and heartbeat skew — how far the slowest task lags
        its role's front-runner), and every executor-pushed metric as a
        labeled gauge."""
        r = PromRenderer()
        roles = sorted(self.session.role_specs)
        now_wall = time.time()
        # same terminal/exit_code filter as the liveness monitor: a final
        # beat racing the result unregister leaves a stale entry, and a
        # finished task must not read as an ever-more-stale straggler
        beats = {}
        for task_id, last in list(self.heartbeats.items()):
            task = self.session.get_task_by_id(task_id)
            if (task is None or task.status.is_terminal()
                    or task.exit_code is not None
                    or task_id in self.session.detached):
                continue
            beats[task_id] = last
        with self._tt_lock:
            for role in roles:
                h = self._gang_hist.setdefault(role, Histogram())
                r.histogram(
                    DRIVER_GANG_LAUNCH_SECONDS, h,
                    "capacity request -> worker registration, per role",
                    labels={"role": role})
            r.histogram(
                DRIVER_HEARTBEAT_INTERVAL_SECONDS, self._hb_hist,
                "observed heartbeat inter-arrival time across all tasks")
            r.counter(DRIVER_TASK_RESTARTS_TOTAL, self._restart_count,
                      "per-task restart budget units spent")
            r.counter(DRIVER_HEARTBEAT_EXPIRED_TOTAL,
                      self._hb_expired_count,
                      "tasks deemed dead after missing the heartbeat "
                      "budget")
            r.counter(DRIVER_TASK_ROLLS_TOTAL, self._roll_count,
                      "deliberate rolling restarts (roll_task RPC; "
                      "budget-free)")
            r.counter(DRIVER_PREEMPTIONS_TOTAL, self._preempt_count,
                      "preemption drains relayed or reported "
                      "(budget-free relaunches)")
            r.counter(DRIVER_GANG_RESIZES_TOTAL, self._resize_count,
                      "elastic gang re-formations (down on worker loss "
                      "past its budget, up when capacity returned)")
            r.counter(DRIVER_WARM_POOL_ADOPTIONS_TOTAL,
                      self._warm_adoptions,
                      "task launches that adopted a pre-warmed standby "
                      "(child_adopted spans)")
            r.counter(DRIVER_WARM_POOL_MISSES_TOTAL, self._warm_misses,
                      "launches with the warm pool configured that fell "
                      "back to a cold spawn")
            r.counter(DRIVER_RECOVERIES_TOTAL, self._recoveries,
                      "driver restarts that recovered this job's "
                      "control plane from driver.journal.jsonl")
            r.counter(DRIVER_TASKS_READOPTED_TOTAL, self._readopted,
                      "live tasks a recovered driver re-adopted "
                      "(heartbeats re-attached) instead of relaunching")
            r.counter(DRIVER_AUTOSCALE_SCALE_UPS_TOTAL,
                      self._scale_up_count,
                      "autoscaler scale-up decisions actuated (parked "
                      "replica slots relaunched)")
            r.counter(DRIVER_AUTOSCALE_SCALE_DOWNS_TOTAL,
                      self._scale_down_count,
                      "autoscaler scale-down decisions actuated "
                      "(replicas SIGTERM-drained, slots parked)")
            if self._router_tier_active():
                # the router-TIER slices of the same families: the
                # unlabeled totals above keep counting EVERY tier (the
                # pre-router contract), the {tier="router"} series
                # break out the front-door fleet's share
                r.counter(DRIVER_AUTOSCALE_SCALE_UPS_TOTAL,
                          self._router_scale_up_count,
                          "autoscaler scale-up decisions actuated",
                          labels={"tier": "router"})
                r.counter(DRIVER_AUTOSCALE_SCALE_DOWNS_TOTAL,
                          self._router_scale_down_count,
                          "autoscaler scale-down decisions actuated",
                          labels={"tier": "router"})
            reg = dict(self._reg_t)
        from .warmpool import count_ready

        r.gauge(DRIVER_WARM_POOL_SIZE,
                count_ready(self._warm_pool.dir
                            if self._warm_pool is not None else None),
                "ready (adoptable) standbys in the driver host's warm "
                "pool; 0 when the pool is off")
        # driver-process XLA compile telemetry (preprocess/notebook jobs
        # run user code in-process); each training CHILD's compile totals
        # arrive as executor-pushed metrics (xla_compiles et al) and
        # render below as driver_task_metric gauges. Re-try the install
        # every scrape: __init__ skipped it while jax was unimported,
        # and user code may have brought jax in since (idempotent,
        # returns the same process-global instance)
        from .observability import install_compile_telemetry

        ct = install_compile_telemetry(only_if_loaded=True)
        comp = ct.snapshot()
        r.histogram("driver_xla_compile_seconds", ct.hist_copy(),
                    "XLA backend compile duration in the driver process")
        r.counter("driver_xla_compiles_total", comp["compiles"],
                  "XLA backend compilations in the driver process")
        # autoscaler view + shared-pool quota accounting (docs/
        # autoscaling.md): rendered whenever the arbiter exists (always)
        # so the pool is scrapeable even before the first decision
        snap = self.arbiter.snapshot()
        r.gauge(DRIVER_QUOTA_POOL_SLOTS, snap["pool_slots"],
                "the shared device/slot pool every role draws from")
        r.gauge(DRIVER_QUOTA_POOL_FREE, snap["free"],
                "pool slots no role currently holds")
        for role_name in snap["held"]:
            for stat, val in (("held", snap["held"][role_name]),
                              ("quota", snap["quota"][role_name])):
                r.gauge(DRIVER_QUOTA_SLOTS, val,
                        "per-role pool occupancy vs quota",
                        labels={"role": role_name, "stat": stat})
        r.counter(DRIVER_QUOTA_DONATIONS_TOTAL, self.arbiter.donations,
                  "batch workers preempt-drained to free pool slots "
                  "for the interactive tier")
        r.counter(DRIVER_QUOTA_RECLAIMS_TOTAL, self.arbiter.reclaims,
                  "donated slots returned to the batch tier after the "
                  "interactive tier scaled back down")
        ctl = self._controller
        if ctl is not None:
            role = self._autoscale_role
            for stat, val in (("current", self.arbiter.held(role)),
                              ("min", ctl.min_replicas),
                              ("max", ctl.max_replicas)):
                r.gauge(DRIVER_AUTOSCALE_REPLICAS, val,
                        "the autoscaled serving role's replica count "
                        "and bounds",
                        labels={"role": role, "stat": stat})
            obs = ctl.last_obs
            r.gauge(DRIVER_AUTOSCALE_TTFT_P99_S,
                    round(obs.ttft_p99_s or 0.0, 6),
                    "newest WINDOWED fleet TTFT p99 the controller "
                    "observed (0 = no completions in the window)")
            r.gauge(DRIVER_AUTOSCALE_QUEUE_DEPTH,
                    max(obs.queued, obs.router_queued or 0),
                    "newest queued-request signal the controller "
                    "observed (max of the replica /stats view and the "
                    "router view — they overlap, never summed)")
            if self._router_tier_active():
                rrole = self._router_role
                for stat, val in (("current", self.arbiter.held(rrole)),
                                  ("min", ctl.router_min),
                                  ("max", ctl.router_max)):
                    r.gauge(DRIVER_AUTOSCALE_REPLICAS, val,
                            "the autoscaled serving role's replica "
                            "count and bounds",
                            labels={"role": rrole, "stat": stat,
                                    "tier": "router"})
                r.gauge(DRIVER_AUTOSCALE_QUEUE_DEPTH,
                        obs.router_relay_inflight,
                        "newest queued-request signal the controller "
                        "observed",
                        labels={"tier": "router"})
        # scrape-pipeline health: failed fetches per target, from the
        # watcher's fetch path and the hub's alike — a half-blind
        # control loop (replica up, /metrics refusing) is VISIBLE here
        # instead of silently retaining a stale baseline
        failures: dict[str, int] = {}
        runner = self._autoscale_runner
        if runner is not None and runner.watcher is not None:
            failures.update(runner.watcher.scrape_failures)
        hub = self._metrics_hub
        if hub is not None:
            for target, n in hub.failures.items():
                failures[target] = failures.get(target, 0) + n
        for target in sorted(failures):
            r.counter(DRIVER_AUTOSCALE_SCRAPE_FAILURES_TOTAL,
                      failures[target],
                      "scrape fetches that failed, per target "
                      "(watcher + metrics hub)",
                      labels={"target": target})
        if hub is not None:
            r.counter(DRIVER_METRICSHUB_SCRAPES_TOTAL,
                      hub.scrapes_total,
                      "exposition payloads the metrics hub ingested")
            r.gauge(DRIVER_METRICSHUB_SERIES, len(hub._series),
                    "distinct series retained in the hub's rings")
            r.gauge(DRIVER_METRICSHUB_TARGETS, len(hub.targets()),
                    "scrape targets the hub has ever ingested")
        if self._slo_engine is not None:
            # driver_slo_burn_rate / _error_budget_remaining /
            # _alerts_firing from the newest evaluation
            self._slo_engine.render_into(r)
        counts: dict[str, int] = {}
        for t in self.session.all_tasks():
            counts[t.status.value] = counts.get(t.status.value, 0) + 1
        # detached is a formation state, not a task status: a slot can be
        # RUNNING *and* detached mid-drain — render it as its own series
        counts["detached"] = len(self.session.detached)
        for status in sorted(counts):
            r.gauge(DRIVER_TASKS, counts[status], "tasks by state",
                    labels={"state": status})
        # checkpoint recency per task (pushed ckpt_unix_ts from the
        # training child's StepTimer records): how many seconds of
        # training this worker would recompute if it died right now.
        # Cross-host NTP skew shifts it like every executor wall-clock
        # sample; the bound it guards is seconds-scale, skew is ms-scale.
        from .metrics import CKPT_UNIX_TS

        for task_id in sorted(self.metrics):
            ts = self._pushed_metric(task_id, f"max_{CKPT_UNIX_TS}")
            if ts:
                r.gauge(DRIVER_CHECKPOINT_AGE_S,
                        round(max(0.0, now_wall - ts), 3),
                        "age of the newest checkpoint each worker "
                        "reported (StepTimer note_checkpoint)",
                        labels={"task": task_id})
        for task_id, ports in sorted(self.session.service_ports().items()):
            for pname, port in sorted(ports.items()):
                r.gauge(DRIVER_TASK_SERVICE_PORT, port,
                        "named service ports tasks published "
                        "(publish_ports RPC)",
                        labels={"task": task_id, "name": pname})
        for role in roles:
            rts = [v for tid, v in reg.items()
                   if tid.partition(":")[0] == role]
            lo = min(rts) if rts else 0.0
            for stat, val in _lag_stats([v - lo for v in rts]).items():
                r.gauge(DRIVER_STRAGGLER_REGISTRATION_S, val,
                        "registration lag behind the role's first "
                        "registrant (gang-launch straggler gauge)",
                        labels={"role": role, "stat": stat})
            bts = [v for tid, v in beats.items()
                   if tid.partition(":")[0] == role]
            hi = max(bts) if bts else now_wall
            for stat, val in _lag_stats([hi - v for v in bts]).items():
                r.gauge(DRIVER_STRAGGLER_HEARTBEAT_S, val,
                        "heartbeat staleness behind the role's freshest "
                        "beat (liveness straggler gauge)",
                        labels={"role": role, "stat": stat})
        for task_id in sorted(self.metrics):
            for entry in self.metrics[task_id]:
                name, value = entry.get("name"), entry.get("value")
                if name is None or not isinstance(value, (int, float)):
                    continue
                r.gauge(DRIVER_TASK_METRIC, value,
                        "executor-pushed metric snapshot (max_/avg_ "
                        "per name)",
                        labels={"task": task_id, "name": name})
        return r.render()

    # ------------------------------------------------------------ completion
    def _on_container_completed(self, handle: ContainerHandle, exit_code: int) -> None:
        """Provisioner watcher callback — reference
        processFinishedContainer:1238-1274."""
        task_id = f"{handle.role}:{handle.index}"
        # fault injection: hold back the completion notification so heartbeat
        # expiry races it (reference TEST_TASK_COMPLETION_NOTIFICATION_DELAYED,
        # ApplicationMaster.java:1075-1087); runs on the per-container watcher
        # thread, so sleeping stalls only this callback
        try:
            delay_ms = int(os.environ.get(c.TEST_COMPLETION_DELAY_MS, "0"))
        except ValueError:
            # a bad test knob must degrade to no-delay, not swallow the
            # completion callback and hang the job
            log.error("bad %s value; ignoring", c.TEST_COMPLETION_DELAY_MS)
            delay_ms = 0
        if delay_ms:
            log.warning("fault injection: delaying completion of %s by %dms",
                        task_id, delay_ms)
            time.sleep(delay_ms / 1000)
        # a superseded attempt's container (e.g. one killed after its
        # heartbeat death already triggered an in-place restart) completes
        # AFTER the replacement launched: its exit must not burn the new
        # attempt's restart budget or fail the job out from under it.
        # Guard + result handling run under the restart lock so the
        # staleness read and any restart it triggers are atomic vs the
        # monitor thread's heartbeat-expiry restart.
        with self._restart_lock:
            current = self._handles.get(task_id)
            if current is None or current.container_id != handle.container_id:
                log.info(
                    "ignoring completion of superseded container %s for %s",
                    handle.container_id, task_id)
                return
            self.on_task_result(task_id, exit_code, source="container")

    def on_task_result(self, task_id: str, exit_code: int, source: str) -> None:
        task = self.session.get_task_by_id(task_id)
        if task is None:
            return
        if source == "executor":
            # informational: the authoritative completion is the container
            # exit (reference records registerExecutionResult but completes
            # tasks from the RM callback, processFinishedContainer:1238-1274).
            # The task stops heartbeating now, so unregister it from liveness
            # — otherwise a delayed completion notification lets heartbeat
            # expiry declare a finished task dead and fail the job (the race
            # the reference's HB-unregister handling covers, AM:1075-1087)
            task.exit_code = exit_code
            self.heartbeats.pop(task_id, None)
            # ...EXCEPT for a RE-ADOPTED container (driver recovery): the
            # old driver's Popen watcher died with it, so no container
            # callback will ever come — the executor's own report IS the
            # completion. Run it through the container path under the
            # restart lock, like the watcher would have.
            with self._restart_lock:
                handle = self._handles.get(task_id)
                if handle is not None and handle.extra.get("adopted"):
                    self.on_task_result(task_id, exit_code,
                                        source="container")
            return
        if (
            source == "container"
            and not task.status.is_terminal()
            and not self._stop_requested.is_set()
        ):
            # a deliberate roll relaunches on ANY exit code (the drained
            # serve child exits 0, its executor 137) without touching
            # the budget; so do a preemption drain and a resize drain —
            # all three are ledgered, deliberate exits, not failures.
            # Failures then fall through to the budgeted path, and a
            # budget-exhausted loss tries the elastic resize before the
            # completion policy gets to fail the job.
            if self._discharge_scale_down(task_id):
                return
            if self._discharge_donation(task_id):
                return
            if self._discharge_roll(task_id):
                return
            if self._discharge_resize(task_id):
                return
            if self._discharge_preempt(task_id, exit_code):
                return
            if exit_code != 0 and self._try_restart_task(task_id, exit_code):
                return
            if (exit_code != 0
                    and self._park_failed_replica(
                        task_id, cause=f"exited {exit_code}")):
                return
            if (exit_code != 0 and self._elastic_candidate(task_id)
                    and self._resize_down(task_id,
                                          cause=f"exited {exit_code}")):
                return
        already_terminal = task.status.is_terminal()
        name, _, idx = task_id.partition(":")
        self.session.on_task_completed(name, int(idx), exit_code)
        if not already_terminal:
            self._jrec("terminal", task=task_id, status=task.status.value,
                       exit_code=exit_code)
            self._seal_task_trace(
                task_id, "finished" if exit_code == 0 else "failed",
                exit_code=exit_code, status=task.status.value)
            if self.events:
                self.events.emit(
                    task_finished(
                        task_id, task.status.value, exit_code,
                        metrics=self.metrics.get(task_id, []),
                    )
                )
            if self.scheduler:
                self.scheduler.on_task_completed(name, exit_code == 0)

    def _try_restart_task(self, task_id: str, exit_code: int,
                          cause: str = "") -> bool:
        """Per-task restart within the same session — a recovery capability
        the reference lacks (it only supports whole-job AM retry,
        SURVEY.md §5). Budgeted by tony.<role>.max-restarts; both container
        exits and heartbeat deaths (``cause``) spend from the same budget."""
        name, _, idx = task_id.partition(":")
        spec = self.session.role_specs.get(name)
        if spec is None or spec.max_restarts <= 0:
            return False
        used = self._restarts.get(task_id, 0)
        if used >= spec.max_restarts:
            return False
        # a FAILURE restart supersedes any pending roll/preempt/resize
        # ledger entry: the wedged/crashed attempt is being replaced
        # right here, and a stale entry would mislabel the NEXT crash as
        # a budget-free relaunch
        self._rolls.discard(task_id)
        self._preempts.discard(task_id)
        self._preempt_cmds.discard(task_id)
        self._resizes.discard(task_id)
        self._straggler_strikes.pop(task_id, None)
        self._restarts[task_id] = used + 1
        self._jrec("restarts", task=task_id, used=used + 1)
        log.warning(
            "task %s %s; restarting (%d/%d)",
            task_id, cause or f"exited {exit_code}",
            used + 1, spec.max_restarts,
        )
        # the trace keeps accumulating across attempts: a "restarted"
        # mark (n-th budget unit), then the new attempt's full
        # requested->registered chain repeats in the same record
        with self._tt_lock:
            self._restart_count += 1
        self._clear_attempt_state(task_id)
        self._trace_mark(task_id, "restarted", restarts=used + 1,
                         last_cause=cause or f"exited {exit_code}")
        self._relaunch_task(task_id, spec, int(idx))
        return True

    def _bump_attempt(self, task_id: str) -> int:
        """Next launch ordinal for a task — stamped into the attempt's
        env and journaled with the launch, so zombie registrations from
        superseded attempts are refusable by number."""
        att = self._attempts.get(task_id, 0) + 1
        self._attempts[task_id] = att
        return att

    def _journal_launch(self, task_id: str, handle: ContainerHandle) -> None:
        self._jrec("launch", task=task_id,
                   attempt=self._attempts.get(task_id, 0),
                   container_id=handle.container_id,
                   pid=_handle_pid(handle), host=handle.host,
                   t=time.time(),
                   log_path=str(handle.extra.get("log_path", "")))

    def _relaunch_task(self, task_id: str, spec: RoleSpec, idx: int,
                       extra_env: dict[str, str] | None = None) -> None:
        """Launch a fresh attempt of an existing task (restart or roll):
        new container, fresh liveness, stale published ports dropped.
        ``extra_env`` rides this attempt only (e.g. the rescale path's
        TONY_PRESTAGE_CKPT)."""
        task = self.session.get_task_by_id(task_id)
        task.status = TaskStatus.REQUESTED
        task.exit_code = None  # re-arm heartbeat liveness for the new attempt
        # fresh attempt, clean slate: a deliberate-stop marker or a LATE
        # preemption report from the superseded attempt (the executor's
        # notify can straggle behind its own exit) must not leak onto
        # the replacement — a stale _preempts entry would let the new
        # attempt's first genuine crash escape the restart budget
        self._driver_stops.discard(task_id)
        self._preempts.discard(task_id)
        self._preempt_cmds.discard(task_id)
        # the old attempt's published service ports are dead endpoints;
        # consumers (the fleet router's discovery) must not route to them
        task.ports.clear()
        task.launch_path = ""   # the NEW attempt reports its own path
        self._trace_mark(task_id, "requested")
        env = self._task_env(spec, idx)
        if extra_env:
            env.update(extra_env)
        env[c.ENV_TASK_ATTEMPT] = str(self._bump_attempt(task_id))
        # same launch/handle atomicity as _request_role (reentrant: the
        # discharge paths already hold the lock)
        with self._restart_lock:
            handle = self.provisioner.launch(
                spec, idx, env, self.job_dir / "logs")
            self._handles[task_id] = handle
        self.session.note_allocated(task_id, handle.container_id)
        self._journal_launch(task_id, handle)
        self._trace_mark(task_id, "allocated", host=handle.host)
        self._launch_ms[task_id] = now_ms()
        self._trace_mark(task_id, "launched")
        self.heartbeats.pop(task_id, None)
        if self.events:
            self.events.emit(task_started(task_id, handle.host))

    # ------------------------------------------------------- serving rolls
    def publish_task_ports(self, task_id: str, ports: dict) -> bool:
        """publish_ports RPC body: merge the named ports into the task's
        session entry and record them on its lifecycle trace."""
        if not self.session.set_task_ports(task_id, ports):
            return False
        self._jrec("ports", task=task_id,
                   ports={str(k): int(v) for k, v in (ports or {}).items()})
        with self._tt_lock:
            tr = self.task_traces.get(task_id)
            if tr is not None:
                merged = dict(tr.attrs.get("ports", {}))
                merged.update({str(k): int(v) for k, v in ports.items()})
                tr.attrs["ports"] = merged
        log.info("%s published service ports %s", task_id, dict(ports))
        return True

    def roll_task(self, task_id: str) -> bool:
        """Deliberate rolling restart (roll_task RPC): SIGTERM the
        container so a draining child (serving replica) finishes its
        in-flight work, then relaunch without spending restart budget.
        False for unknown / not-yet-running / terminal tasks.

        Drain continuity relies on the EXECUTOR exiting promptly on
        SIGTERM (it does — sys.exit in its handler): the provisioner
        escalates to a group SIGKILL only if the executor lingers past
        its stop wait, and THAT would take the draining serve child
        with it. The orphaned child keeps draining up to its own
        --drain-timeout-s either way."""
        task = self.session.get_task_by_id(task_id)
        if task is None or task.status != TaskStatus.RUNNING:
            return False
        with self._restart_lock:
            handle = self._handles.get(task_id)
            if handle is None:
                return False
            self._rolls.add(task_id)
        self._jrec("ledger", kind="roll", task=task_id)
        log.info("rolling %s (SIGTERM drain, budget-free relaunch)", task_id)
        # the stop can wait several seconds on a slow drain; do it off the
        # RPC thread so the caller gets its ack immediately
        threading.Thread(target=self.provisioner.stop_container,
                         args=(handle,), name=f"roll-{task_id}",
                         daemon=True).start()
        return True

    def _discharge_roll(self, task_id: str) -> bool:
        """Container completion of a task mid-roll: relaunch without
        charging the budget; the trace records a ``rolled`` mark and the
        fresh attempt chain. Caller holds the restart lock (container-
        completion path)."""
        if task_id not in self._rolls:
            return False
        self._rolls.discard(task_id)
        name, _, idx = task_id.partition(":")
        spec = self.session.role_specs.get(name)
        if spec is None:
            return False
        with self._tt_lock:
            self._roll_count += 1
        self._clear_attempt_state(task_id)
        self._trace_mark(task_id, "rolled")
        self._relaunch_task(task_id, spec, int(idx))
        return True

    # ---------------------------------------- autoscaler + resource arbiter
    def _role_class(self, role: str) -> str:
        spec = self.session.role_specs.get(role)
        return getattr(spec, "priority_class", "interactive") \
            if spec is not None else "interactive"

    def _router_tier_active(self) -> bool:
        """Is the router TIER under the controller's law (a router role
        exists and ``tony.autoscale.router-relay-slo`` armed it)? Gates
        the park-don't-fail treatment of budget-exhausted routers: a
        parked front door with no law to un-park it would be a silent
        capacity leak."""
        ctl = self._controller
        return bool(self._router_role and ctl is not None
                    and ctl.router_slo > 0)

    def _hub_targets(self) -> list:
        """The metrics hub's scrape-target discovery: every tier's
        exposition surface known to the session table — the serving
        role's replicas and the router role's front doors (their
        published serve_port's /metrics), plus the driver's own
        renderer IN-PROCESS (no HTTP hop for the tier hosting the
        hub)."""
        targets: list = [("driver", self.render_metrics)]
        seen = {"driver"}
        for role in (self._autoscale_role, self._router_role):
            if not role:
                continue
            for name, host, port in self.serving_endpoints(role):
                if name in seen:
                    continue
                seen.add(name)
                targets.append((name, f"http://{host}:{port}/metrics"))
        return targets

    def _slo_record(self, slo: str, severity: str, state: str,
                    t: float) -> None:
        """Journal one alert transition (the SLO engine's record_fn) —
        best-effort under the journal contract."""
        self._jrec("slo_alert", slo=slo, severity=severity, state=state,
                   t=t)

    def _slo_eval(self) -> None:
        """One SLO evaluation pass (hub scrape-round callback)."""
        if self._slo_engine is not None:
            try:
                self._slo_engine.evaluate()
            except Exception:
                log.exception("slo evaluation failed")

    def _start_metricshub(self) -> None:
        """Build the fleet metrics hub + SLO engine (prepare(); no-op
        when neither autoscaling nor declared SLOs need them). The hub
        persists its rings to metrics.tsdb.jsonl in the job dir; a
        recovered driver replays the file so alert windows and error
        budgets keep their history, and seeds the engine's alert state
        from the journal so a mid-incident alert RESUMES firing
        without a duplicate transition."""
        from .metricshub import MetricsHub
        from .slo import SLOEngine, slo_objectives_from_conf

        objectives = slo_objectives_from_conf(self.conf)
        if not objectives and not (self._autoscale_enabled
                                   and self._autoscale_role):
            return
        retention = float(
            self.conf.get(keys.SLO_HUB_RETENTION_S, 900) or 900)
        if objectives:
            # the rings must hold every window the objectives burn over
            retention = max(retention,
                            *(s.window_s * 1.05 for s in objectives))
        self._metrics_hub = MetricsHub(
            persist_dir=self.job_dir, retention_s=retention,
            max_points=self.conf.get_int(keys.SLO_HUB_MAX_POINTS, 720))
        if self._recovered_state is not None:
            n = self._metrics_hub.load()
            if n:
                log.info("metrics hub replayed %d tsdb record(s)", n)
        if objectives:
            initial = {}
            if self._recovered_state is not None:
                for key, entry in getattr(self._recovered_state,
                                          "slo_alerts", {}).items():
                    name, _, sev = key.rpartition(":")
                    if name and sev:
                        initial[(name, sev)] = (
                            entry.get("state") == "firing")
            self._slo_engine = SLOEngine(
                self._metrics_hub, objectives,
                record_fn=self._slo_record, initial_alerts=initial)
            if initial and any(initial.values()):
                log.info("slo engine resumed %d firing alert(s) from "
                         "the journal",
                         sum(1 for v in initial.values() if v))
        # the hub's own jittered scrape loop covers what the
        # autoscaler's watcher does not (router /metrics, the driver's
        # own families) — and everything, when no autoscaler runs
        self._metrics_hub.start(
            self._hub_targets,
            interval_s=float(
                self.conf.get(keys.SLO_SCRAPE_INTERVAL_S, 5) or 5),
            on_round=self._slo_eval)

    def _start_autoscaler(self) -> None:
        """Start the driver-resident autoscale loop (prepare(); no-op
        when disabled). The controller's cooldown clock resumes from
        the journal's newest scale decision, so a recovered driver
        continues mid-cooldown instead of flapping."""
        if not self._autoscale_enabled or not self._autoscale_role:
            return
        if self._autoscale_runner is not None:
            return
        from .autoscale import AutoscaleController, AutoscaleRunner

        controller = AutoscaleController.from_conf(
            self.conf, last_scale_t=self._recovered_scale_t)
        if self.conf.get_int(keys.AUTOSCALE_MAX, 0) <= 0:
            spec = self.session.role_specs.get(self._autoscale_role)
            controller.max_replicas = max(
                controller.min_replicas,
                spec.instances if spec is not None else 1)
        if controller.router_slo > 0 and self._router_role:
            # the router ceiling is the role's configured instance
            # count — there is no tony.autoscale.router-max key; the
            # job file's `tony.<role>.instances` IS the front-door
            # budget, and slots above router-min start parked
            rspec = self.session.role_specs.get(self._router_role)
            controller.router_max = max(
                controller.router_min,
                rspec.instances if rspec is not None else 1)
        self._controller = controller
        # hub-backed watcher: the controller's /metrics fetches route
        # through the hub's scrape (one pipeline feeds the control law,
        # the SLO engine, the portal, and bench); window math is
        # byte-identical — the hub hands back the raw exposition body
        from .autoscale import FleetWatcher
        self._autoscale_runner = AutoscaleRunner(
            self, controller,
            watcher=FleetWatcher(hub=self._metrics_hub),
            router_stats_url=str(
                self.conf.get(keys.AUTOSCALE_ROUTER_STATS_URL, "") or ""))
        self._autoscale_runner.start()
        log.info(
            "autoscaler on for role %s: min=%d max=%d ttft_slo=%ss "
            "queue_slo=%s cooldown=%ss pool=%d slots",
            self._autoscale_role, controller.min_replicas,
            controller.max_replicas, controller.ttft_slo_s,
            controller.queue_slo, controller.cooldown_s,
            self.arbiter.pool_slots)

    def serving_endpoints(self, role: str) -> list[tuple[str, str, int]]:
        """The role's live serving endpoints: RUNNING, non-detached
        tasks that published a ``serve_port`` — the controller's
        telemetry targets (same filter as the router's discovery)."""
        out = []
        for task in self.session.tasks.get(role, []):
            if task.task_id in self.session.detached:
                continue
            if task.status != TaskStatus.RUNNING:
                continue
            port = task.ports.get("serve_port")
            if not port:
                continue
            out.append((task.task_id, task.host or "127.0.0.1", int(port)))
        return out

    def autoscale_tick(self, controller, watcher,
                       router_stats_url: str = "") -> str:
        """One controller tick: observe the fleet, evaluate the control
        law, actuate. Returns a status string (telemetry/testing):
        "idle" (no decision), "scaled_up"/"scaled_down" (actuated),
        "awaiting_donation" (capacity requested from the batch tier,
        drain in flight), "no_capacity"/"quota"/"at_max" (denied)."""
        role = self._autoscale_role
        if not role or self._stop_requested.is_set():
            return "idle"
        router_role = (self._router_role
                       if controller.router_slo > 0 else "")
        obs = watcher.observe(
            self.serving_endpoints(role), router_stats_url,
            router_endpoints=(self.serving_endpoints(router_role)
                              if router_role else ()))
        with self._restart_lock:
            draining = sum(1 for t in self._scale_downs
                           if t.partition(":")[0] == role)
            r_draining = sum(1 for t in self._scale_downs
                             if t.partition(":")[0] == router_role)
        # the control law sees the POST-drain fleet size: a replica
        # mid-scale-down-drain still counts as RUNNING in the session
        # table, and counting it would let a second scale-down fire
        # past the cooldown while the first drain is in flight —
        # draining the whole fleet. Same arithmetic for front doors.
        decision = controller.decide(
            obs, self.arbiter.held(role) - draining,
            n_routers=(self.arbiter.held(router_role) - r_draining
                       if router_role else None))
        if decision is None:
            return "idle"
        if decision.direction == "up":
            status = self._autoscale_scale_up(decision.reason,
                                              tier=decision.tier)
            if status == "scaled":
                controller.note_scaled("up")
                self._push_autoscale_hint(controller)
                return "scaled_up"
            if status == "launch_failed":
                # arm the cooldown anyway: a persistent provisioner
                # failure must not journal a fresh "up" op every tick
                controller.note_scaled("up")
                self._push_autoscale_hint(controller)
            return status
        if decision.tier == "router":
            victim = self._pick_scale_down_victim(
                router_role, watcher.last_router_loads)
        else:
            victim = self._pick_scale_down_victim(role,
                                                  watcher.last_loads)
        if victim is not None and self._autoscale_scale_down(
                victim, decision.reason, tier=decision.tier):
            controller.note_scaled("down")
            self._push_autoscale_hint(controller)
            return "scaled_down"
        return "idle"

    def _push_autoscale_hint(self, controller) -> None:
        """Broadcast the freshly armed cooldown to every serving
        replica (POST /autoscale/hint, best effort): their 429
        ``Retry-After`` headers then advertise AT LEAST the window in
        which the fleet cannot add capacity, so shed clients stop
        hammering a fleet that is already scaling. The hint decays
        replica-side, so a missed broadcast only costs accuracy."""
        import json as _json
        import urllib.request as _urlreq

        cooldown = controller.cooldown_remaining()
        body = _json.dumps({"cooldown_s": cooldown}).encode()
        for task_id, host, port in self.serving_endpoints(
                self._autoscale_role):
            try:
                req = _urlreq.Request(
                    f"http://{host}:{port}/autoscale/hint", data=body,
                    headers={"Content-Type": "application/json"})
                with _urlreq.urlopen(req, timeout=1.0):
                    pass
            except Exception:
                log.debug("autoscale hint push to %s failed", task_id,
                          exc_info=True)

    def _pick_scale_down_victim(self, role: str,
                                loads: dict) -> str | None:
        """The least-loaded RUNNING replica (instantaneous queued +
        active from the watcher's newest poll; unknown load sorts
        first — an unpolled replica is at worst idle), highest index on
        ties so the fleet shrinks from the top like it grew."""
        candidates = [
            t for t in self.session.tasks.get(role, [])
            if t.task_id not in self.session.detached
            and t.status == TaskStatus.RUNNING
            and t.task_id not in self._scale_downs
            and t.task_id not in self._rolls]
        if not candidates:
            return None
        return min(candidates,
                   key=lambda t: (loads.get(t.task_id, 0), -t.index)).task_id

    def _tier_match(self, index: int, tier: str) -> bool:
        """Does a replica slot's task index fall in ``tier``'s range?
        Tiers are carved by index (runtimes/serving.py _role_flags):
        the first ``tony.serving.prefill-instances`` slots launch
        ``--role prefill``, the next ``decode-instances`` launch
        ``--role decode``. Empty tier matches everything."""
        if not tier:
            return True
        n_prefill = max(0, self.conf.get_int(
            keys.SERVING_PREFILL_INSTANCES, 0))
        n_decode = max(0, self.conf.get_int(
            keys.SERVING_DECODE_INSTANCES, 0))
        if tier == "prefill":
            return index < n_prefill
        if tier == "decode":
            return n_prefill <= index < n_prefill + n_decode
        return True

    def _autoscale_scale_up(self, reason: str, tier: str = "") -> str:
        """Claim a parked slot for the serving role. When the pool is
        exhausted, ask the arbiter for a batch donor and preempt-drain
        it (budget-free, checkpoint at the step boundary); the actual
        launch happens on a later tick, once the donation's completion
        has freed the slot — the controller keeps its cooldown unarmed
        until then. ``tier`` targets a phase tier of a disaggregated
        fleet (queue breach -> prefill slots, latency breach -> decode
        slots); a tier with no parked slot falls back to any parked
        slot — capacity in the wrong phase still beats a breach (the
        extra replica serves role "both" and absorbs either phase).
        ``tier="router"`` targets the router ROLE instead of the
        serving role (docs/serving.md "Router tier HA"): same parked-
        slot claim, same ledger, different role — front doors have no
        phase sub-tiers, so the index carve does not apply."""
        if tier == "router":
            role, slot_tier = self._router_role, ""
        else:
            role, slot_tier = self._autoscale_role, tier
        spec = self.session.role_specs.get(role)
        if spec is None:
            return "no_role"
        with self._restart_lock:
            if self._stop_requested.is_set():
                return "stopped"
            parked = sorted(
                (t for t in self.session.tasks.get(role, [])
                 if t.task_id in self._parked
                 and t.task_id in self.session.detached),
                key=lambda t: t.index)
            if slot_tier:
                in_tier = [t for t in parked
                           if self._tier_match(t.index, slot_tier)]
                if in_tier:
                    parked = in_tier
                elif parked:
                    log.warning(
                        "autoscale: no parked %s-tier slot; claiming "
                        "%s outside the tier instead", slot_tier,
                        parked[0].task_id)
            if not parked:
                return "at_max"
            if not self.arbiter.can_grant(role):
                if self.arbiter.over_quota(role):
                    return "quota"
                if role in self._donations.values():
                    # a donation drain is already in flight for this
                    # role; its discharge hands the slot over directly
                    return "awaiting_donation"
                busy = (set(self._donations) | self._resizes
                        | self._rolls | self._preempts
                        | self._scale_downs)
                donor = self.arbiter.pick_donor(
                    role, elastic_min=self._elastic_min, busy=busy)
                if donor is None:
                    log.warning(
                        "autoscale: %s wants capacity (%s) but the pool "
                        "is exhausted and no batch donor qualifies",
                        role, reason)
                    return "no_capacity"
                if self._initiate_donation(donor, role, reason):
                    return "awaiting_donation"
                return "no_capacity"
            task = parked[0]
            task_id = task.task_id
            self.session.reattach_task(task_id)
            self._parked.discard(task_id)
            self._detach_t.pop(task_id, None)
            self._jrec("reattach", task=task_id)
            self._jrec("unpark", task=task_id)
            # the decision ledger: journaled BEFORE the launch so a
            # driver killed mid-actuation recovers the cooldown clock
            self._jrec("scale", dir="up", task=task_id, t=time.time(),
                       reason=reason, tier=tier)
            with self._tt_lock:
                self._scale_up_count += 1
                if tier == "router":
                    self._router_scale_up_count += 1
            self._clear_attempt_state(task_id)
            self._trace_mark(task_id, "scaled_up", scale_reason=reason)
            log.warning("autoscale: scaling %s UP via %s (%s)", role,
                        task_id, reason)
            try:
                self._relaunch_task(task_id, spec, task.index)
            except Exception:
                # capacity vanished between grant and launch (the
                # _try_rescale_up contract): RE-PARK the slot so the
                # arbiter doesn't count a handle-less task as a live
                # replica forever; the journaled decision keeps the
                # cooldown armed, and the floor rule / next breach
                # retries after it
                log.exception("autoscale: launch of %s failed; "
                              "re-parking the slot", task_id)
                self.session.detach_task(task_id)
                self._parked.add(task_id)
                self._jrec("detach", task=task_id)
                self._jrec("park", task=task_id)
                return "launch_failed"
        return "scaled"

    def _autoscale_scale_down(self, task_id: str, reason: str,
                              tier: str = "") -> bool:
        """SIGTERM-drain one replica (the serve child finishes its
        in-flight requests on the group signal — the roll path's drain
        contract); its completion PARKS the slot instead of
        relaunching. Zero dropped requests by construction: in-flight
        work drains, queued work fails over through the router's
        journal/progress machinery. ``tier="router"`` drains a front
        door the same way — ``tony-tpu route``'s SIGTERM handler stops
        accepting (healthz flips unhealthy, new posts 503 to the other
        doors) and finishes its in-flight relays before exiting 0."""
        task = self.session.get_task_by_id(task_id)
        if task is None or task.status != TaskStatus.RUNNING:
            return False
        with self._restart_lock:
            if (task_id in self._scale_downs or task_id in self._rolls
                    or task_id in self._resizes):
                return False
            handle = self._handles.get(task_id)
            if handle is None:
                return False
            self._scale_downs.add(task_id)
        self._jrec("ledger", kind="scale_down", task=task_id)
        self._jrec("scale", dir="down", task=task_id, t=time.time(),
                   reason=reason, tier=tier)
        with self._tt_lock:
            self._scale_down_count += 1
            if tier == "router":
                self._router_scale_down_count += 1
        log.warning("autoscale: scaling DOWN — draining %s (%s)",
                    task_id, reason)
        threading.Thread(target=self.provisioner.stop_container,
                         args=(handle,), name=f"scale-down-{task_id}",
                         daemon=True).start()
        return True

    def _discharge_scale_down(self, task_id: str) -> bool:
        """Container completion of a replica mid-scale-down drain: park
        the slot (detached, ports cleared so discovery drops the dead
        endpoint) instead of relaunching. Caller holds the restart
        lock."""
        if task_id not in self._scale_downs:
            return False
        self._scale_downs.discard(task_id)
        task = self.session.get_task_by_id(task_id)
        self.session.detach_task(task_id)
        self._parked.add(task_id)
        self._handles.pop(task_id, None)
        self.heartbeats.pop(task_id, None)
        if task is not None:
            task.ports.clear()
        self._jrec("detach", task=task_id)
        self._jrec("park", task=task_id)
        self._trace_mark(task_id, "scaled_down")
        log.info("autoscale: %s drained; slot parked", task_id)
        return True

    def _park_failed_replica(self, task_id: str, cause: str) -> bool:
        """A budget-exhausted autoscaled replica parks (the controller
        relaunches it on its floor rule / next breach) instead of
        failing the whole multi-tenant job. Routers qualify too when
        their tier is autoscaled: the router floor rule un-parks a
        front door the same way the serving floor does a replica.
        Caller holds the restart lock (or no thread races: expiry
        path)."""
        parkable = {self._autoscale_role}
        if self._router_tier_active():
            parkable.add(self._router_role)
        if (not self._autoscale_enabled
                or task_id.partition(":")[0] not in parkable
                or self._stop_requested.is_set()):
            return False
        with self._restart_lock:
            task = self.session.get_task_by_id(task_id)
            if task is None or task.task_id in self.session.detached:
                return False
            self.session.detach_task(task_id)
            self._parked.add(task_id)
            self._handles.pop(task_id, None)
            self.heartbeats.pop(task_id, None)
            task.ports.clear()
        self._jrec("detach", task=task_id)
        self._jrec("park", task=task_id)
        self._trace_mark(task_id, "scaled_down", cause=cause)
        log.warning("autoscale: %s lost past its budget (%s); slot "
                    "parked for the controller", task_id, cause)
        return True

    def _initiate_donation(self, donor: str, for_role: str,
                           reason: str) -> bool:
        """Preempt-drain a batch worker so its slot can serve the
        interactive tier: the PR 9 drain contract (checkpoint at the
        step boundary, budget-free), but the completion DETACHES the
        slot (``_discharge_donation``) instead of relaunching. Caller
        holds the restart lock (reentrant)."""
        if donor in self._donations:
            return True
        if not self.preempt_task(donor):
            return False
        self._donations[donor] = for_role
        self._donation_reasons[donor] = reason
        self._jrec("donate", task=donor, **{"for": for_role})
        log.warning(
            "arbiter: preempt-draining batch worker %s to donate its "
            "slot to %s (%s)", donor, for_role, reason)
        return True

    def _discharge_donation(self, task_id: str) -> bool:
        """Container completion of a donating batch worker: detach the
        slot (freeing pool capacity for the interactive tier), re-form
        the donor's gang at the smaller world size (same-class
        survivors drain budget-free, exactly like a resize), and arm
        the reclaim timer — gated on arbiter free capacity, so the
        slot returns only when serving scales back down. Caller holds
        the restart lock."""
        if task_id not in self._donations:
            return False
        for_role = self._donations.pop(task_id)
        self._preempts.discard(task_id)
        self._preempt_cmds.discard(task_id)
        if not self.session.detach_task(task_id):
            return False
        self._donated.add(task_id)
        self._handles.pop(task_id, None)
        self.heartbeats.pop(task_id, None)
        self._detach_t[task_id] = time.monotonic()
        gen = self.session.begin_generation()
        with self._tt_lock:
            self._resize_count += 1
        self.arbiter.donations += 1
        cls = self._role_class(task_id.partition(":")[0])
        survivors = [
            t.task_id for t in self.session.active_tasks()
            if t.status == TaskStatus.RUNNING and t.task_id != task_id
            and self._role_class(t.name) == cls]
        handles = []
        for tid in survivors:
            self._resizes.add(tid)
            self.heartbeats.pop(tid, None)
            h = self._handles.get(tid)
            if h is not None:
                handles.append(h)
        self._straggler_strikes.clear()
        self._jrec("detach", task=task_id)
        self._jrec("donated", task=task_id)
        self._jrec("generation", gen=gen)
        for tid in survivors:
            self._jrec("ledger", kind="resize", task=tid)
        self._trace_mark(task_id, "donated", gang_generation=gen,
                         donated_to=for_role)
        for tid in survivors:
            self._trace_mark(tid, "resized", gang_generation=gen,
                             donated=task_id)
            self.metrics.pop(tid, None)
        log.warning(
            "arbiter: %s donated its slot to %s (gang generation %d; "
            "%d survivors re-forming)", task_id, for_role, gen,
            len(survivors))
        for h in handles:
            threading.Thread(target=self.provisioner.stop_container,
                             args=(h,), name=f"donate-drain-{h.role}",
                             daemon=True).start()
        # hand the freed slot STRAIGHT to the role the donation was for:
        # waiting for the next controller tick opens a race where the
        # (faster) elastic rescale-retry timer sees free capacity and
        # snatches the slot back for the batch tier — the observed
        # donate->reclaim->donate livelock. The restart lock is
        # reentrant; _autoscale_scale_up finds free() >= 1 and claims a
        # parked slot, and the controller's cooldown arms at the REAL
        # actuation instant.
        reason = self._donation_reasons.pop(
            task_id, f"slot donated by {task_id}")
        status = self._autoscale_scale_up(reason)
        if status in ("scaled", "launch_failed") \
                and self._controller is not None:
            # launch_failed arms the cooldown too (the slot re-parked;
            # retry rides the floor rule / next breach, not a tight loop)
            self._controller.note_scaled("up")
        return True

    # -------------------------------------------------- preemption drain
    def preempt_task(self, task_id: str) -> bool:
        """Relay a preemption notice (preempt_task RPC / chaos): queue a
        one-shot ``preempt`` command on the task's heartbeat response.
        The executor drops the drain flag, the training child checkpoints
        at its next step boundary and exits, and the completion relaunches
        budget-free with a ``preempted`` trace mark. False for unknown /
        not-yet-running / terminal tasks."""
        task = self.session.get_task_by_id(task_id)
        if task is None or task.status != TaskStatus.RUNNING:
            return False
        with self._restart_lock:
            if task_id not in self._handles:
                return False
            first = task_id not in self._preempts
            self._preempts.add(task_id)
            self._preempt_cmds.add(task_id)
        self._jrec("ledger", kind="preempt", task=task_id, cmd=True)
        if first:
            with self._tt_lock:
                self._preempt_count += 1
            self._trace_mark(task_id, "preempting", preempt_source="driver")
        log.warning("preempting %s: drain notice queued on its heartbeat",
                    task_id)
        return True

    def note_preemption(self, task_id: str, source: str = "executor") -> bool:
        """The task's own executor reports an external preemption signal
        (cloud SIGTERM): no command to relay — the executor is already
        draining its child — just mark the pending exit budget-free."""
        task = self.session.get_task_by_id(task_id)
        if (task is None or task.status.is_terminal()
                or self._stop_requested.is_set()):
            return False
        with self._restart_lock:
            if (task_id in self._resizes or task_id in self._rolls
                    or task_id in self._driver_stops):
                # the driver initiated this SIGTERM itself (resize drain,
                # roll, or a deliberate kill); the exit is already
                # accounted for and must not relabel as a preemption
                return True
            first = task_id not in self._preempts
            self._preempts.add(task_id)
        self._jrec("ledger", kind="preempt", task=task_id, cmd=False)
        if first:
            with self._tt_lock:
                self._preempt_count += 1
            self._trace_mark(task_id, "preempting", preempt_source=source)
            log.warning("%s reports preemption (%s); its exit is budget-free",
                        task_id, source)
        return True

    def take_preempt_command(self, task_id: str) -> dict | None:
        """One-shot drain of a pending preempt relay (heartbeat path)."""
        with self._restart_lock:
            if task_id not in self._preempt_cmds:
                return None
            self._preempt_cmds.discard(task_id)
        return {"grace_ms": self.conf.get_int(
            keys.TASK_PREEMPT_GRACE_MS, 3000)}

    def _discharge_preempt(self, task_id: str, exit_code: int) -> bool:
        """Container completion of a preempted task (commanded drain, a
        self-reported external preemption, or an uncommanded
        EXIT_PREEMPTED — the child drained on its own notice): relaunch
        without charging the budget, trace-marked ``preempted``. Caller
        holds the restart lock. The superseded-container guard in
        _on_container_completed already ensured this completion belongs
        to the current attempt, so a racing heartbeat-expiry restart
        cannot double-spend (its relaunch would have replaced the
        handle, making this completion read as superseded)."""
        commanded = task_id in self._preempts
        if not commanded and (exit_code != c.EXIT_PREEMPTED
                              or task_id in self._driver_stops):
            # not preempted: either an ordinary exit, or a child that
            # "drained" because the DRIVER deliberately killed it
            return False
        if exit_code == 0:
            # the child finished training before (or despite) the notice:
            # that is a real completion, not a drain — clear the ledger
            # so the finish is final
            self._preempts.discard(task_id)
            self._preempt_cmds.discard(task_id)
            return False
        if not commanded:
            # self-initiated drain: count it (the commanded paths counted
            # at notice time)
            with self._tt_lock:
                self._preempt_count += 1
        self._preempts.discard(task_id)
        self._preempt_cmds.discard(task_id)
        name, _, idx = task_id.partition(":")
        spec = self.session.role_specs.get(name)
        if spec is None:
            return False
        self._clear_attempt_state(task_id)
        self._trace_mark(task_id, "preempted", exit_code=exit_code)
        log.info("relaunching preempted %s (budget-free)", task_id)
        self._relaunch_task(task_id, spec, int(idx))
        return True

    # ------------------------------------------------ elastic gang resize
    def _elastic_candidate(self, task_id: str) -> bool:
        """May this lost-beyond-budget task be detached instead of
        failing the job? Elastic must be on, the job still live, the
        task a tracked non-chief, and the surviving role population at
        or above the configured floor."""
        if not self._elastic or self._stop_requested.is_set():
            return False
        task = self.session.get_task_by_id(task_id)
        if task is None or task.task_id in self.session.detached:
            return False
        if task.name in self.session.untracked:
            return False
        if self.session.is_chief(task.name, task.index):
            # the chief carries the completion policy and (for jax) rank
            # 0's coordinator — its loss stays fatal
            return False
        survivors = [t for t in self.session.active_tasks()
                     if t.name == task.name and t.task_id != task_id
                     and not t.status.is_terminal()]
        return len(survivors) >= self._elastic_min

    def _resize_down(self, task_id: str, cause: str) -> bool:
        """A worker is gone past its restart budget: detach it, bump the
        gang generation, and drain every surviving RUNNING task so the
        gang re-forms from the latest checkpoints at the smaller world
        size (survivor relaunches are budget-free). The detached slot is
        retried every rescale-retry-ms (_try_rescale_up)."""
        with self._restart_lock:
            if self._stop_requested.is_set():
                return False
            if not self.session.detach_task(task_id):
                return False
            old = self._handles.pop(task_id, None)
            self.heartbeats.pop(task_id, None)
            self._preempts.discard(task_id)
            self._preempt_cmds.discard(task_id)
            self._detach_t[task_id] = time.monotonic()
            gen = self.session.begin_generation()
            with self._tt_lock:
                self._resize_count += 1
            # the gang that re-forms is the lost task's TIER: in a
            # multi-tenant job (batch trainers + interactive serving
            # replicas sharing the pool, docs/autoscaling.md), a
            # trainer's resize must not drain the serving fleet
            cls = self._role_class(task_id.partition(":")[0])
            survivors = [
                t.task_id for t in self.session.active_tasks()
                if t.status == TaskStatus.RUNNING and t.task_id != task_id
                and self._role_class(t.name) == cls
            ]
            handles = []
            for tid in survivors:
                self._resizes.add(tid)
                self.heartbeats.pop(tid, None)
                h = self._handles.get(tid)
                if h is not None:
                    handles.append(h)
            # the straggler ledger is attempt-scoped: a drained survivor
            # must not inherit its predecessor's strikes
            self._straggler_strikes.clear()
        self._jrec("detach", task=task_id)
        self._jrec("generation", gen=gen)
        for tid in survivors:
            self._jrec("ledger", kind="resize", task=tid)
        log.warning(
            "elastic resize DOWN to generation %d: %s lost (%s); draining "
            "%d survivors to re-form at the smaller world size",
            gen, task_id, cause, len(survivors))
        self._trace_mark(task_id, "resized", gang_generation=gen,
                         resize="detached", resize_cause=cause)
        for tid in survivors:
            self._trace_mark(tid, "resized", gang_generation=gen,
                             resize="down", lost=task_id)
            self.metrics.pop(tid, None)   # stale step stats must not
            #                               re-flag the fresh attempt
        # stops happen OFF the lock and on their own threads: a slow or
        # SIGTERM-ignoring process costs its own grace window, not a
        # stall of every other completion (same discipline as rolls)
        if old is not None:
            threading.Thread(target=self.provisioner.stop_container,
                             args=(old,), name=f"resize-stop-{task_id}",
                             daemon=True).start()
        for h in handles:
            threading.Thread(target=self.provisioner.stop_container,
                             args=(h,), name=f"resize-drain-{h.role}",
                             daemon=True).start()
        return True

    def _discharge_resize(self, task_id: str) -> bool:
        """Container completion of a survivor draining for a resize:
        budget-free relaunch into the new gang generation. Caller holds
        the restart lock."""
        if task_id not in self._resizes:
            return False
        self._resizes.discard(task_id)
        # a drain SIGTERM looks like a cloud preemption to the executor,
        # which dutifully reports it — the resize ledger owns this exit,
        # and a stale preempt entry would relaunch the NEXT (real)
        # completion too
        self._preempts.discard(task_id)
        self._preempt_cmds.discard(task_id)
        name, _, idx = task_id.partition(":")
        spec = self.session.role_specs.get(name)
        if spec is None:
            return False
        self._clear_attempt_state(task_id)
        self._relaunch_task(task_id, spec, int(idx))
        return True

    def _try_rescale_up(self) -> None:
        """Monitor-loop hook: a detached slot whose retry timer elapsed
        is re-attached — survivors drain again and the whole gang
        re-registers at the restored world size. If the provisioner
        still cannot place it (launch raises), the slot detaches again
        and the timer re-arms."""
        if not self._detach_t or self._stop_requested.is_set():
            return
        now = time.monotonic()
        candidate = None
        for task_id, t0 in self._detach_t.items():
            if now - t0 < self._rescale_retry_s:
                continue
            if task_id in self._parked:
                # an autoscaler-parked slot is the CONTROLLER's to
                # relaunch, never the rescale timer's
                continue
            if task_id in self._donated and self.arbiter.free() < 1:
                # a donated slot returns only once the interactive
                # tier has scaled back down and freed pool capacity
                continue
            candidate = task_id
            break
        if candidate is None:
            return
        task_id = candidate
        name, _, idx = task_id.partition(":")
        spec = self.session.role_specs.get(name)
        if spec is None:
            self._detach_t.pop(task_id, None)
            return
        with self._restart_lock:
            if self._stop_requested.is_set():
                return
            self._detach_t.pop(task_id, None)
            if not self.session.reattach_task(task_id):
                return
            gen = self.session.begin_generation()
            with self._tt_lock:
                self._resize_count += 1
            # the returned slot is fresh capacity: its crash-loop budget
            # starts over (the spent budget belonged to the lost host)
            self._restarts.pop(task_id, None)
            reclaimed = task_id in self._donated
            if reclaimed:
                self._donated.discard(task_id)
                self.arbiter.reclaims += 1
                self._jrec("reclaimed", task=task_id)
            cls = self._role_class(task_id.partition(":")[0])
            survivors = [
                t.task_id for t in self.session.active_tasks()
                if t.status == TaskStatus.RUNNING and t.task_id != task_id
                and self._role_class(t.name) == cls
            ]
            handles = []
            for tid in survivors:
                self._resizes.add(tid)
                self.heartbeats.pop(tid, None)
                h = self._handles.get(tid)
                if h is not None:
                    handles.append(h)
            self._straggler_strikes.clear()
        self._jrec("reattach", task=task_id)
        self._jrec("generation", gen=gen)
        for tid in survivors:
            self._jrec("ledger", kind="resize", task=tid)
        log.warning(
            "elastic resize UP to generation %d: re-adding %s; draining "
            "%d survivors to re-form at the restored world size",
            gen, task_id, len(survivors))
        self._trace_mark(task_id, "resized", gang_generation=gen,
                         resize="rejoined")
        if reclaimed:
            # the arbiter's capacity-return path: the batch tier gets
            # its donated slot back now that serving has scaled down
            self._trace_mark(task_id, "reclaimed", gang_generation=gen)
            log.warning("arbiter: reclaiming donated slot %s for the "
                        "batch tier", task_id)
        for tid in survivors:
            self._trace_mark(tid, "resized", gang_generation=gen,
                             resize="up", rejoined=task_id)
            self.metrics.pop(tid, None)
        # checkpoint-aware rescale placement (docs/autoscaling.md): the
        # returning worker restores (pre-reads) the newest checkpoint
        # BEFORE registering, so the re-formed gang's barrier opens
        # onto a worker whose checkpoint bytes are already local
        extra_env = {}
        ckpt_dir = str(self.conf.get(keys.TRAIN_CKPT_DIR, "") or "")
        if ckpt_dir:
            extra_env[c.ENV_PRESTAGE_CKPT] = ckpt_dir
        try:
            with self._restart_lock:
                self._relaunch_task(task_id, spec, int(idx),
                                    extra_env=extra_env)
        except Exception as e:
            # capacity still gone: fall back to the smaller formation —
            # survivors are already draining and will re-register into
            # the current generation, which excludes the re-detached slot
            log.warning("rescale-up launch of %s failed (%s); staying "
                        "at the smaller world size", task_id, e)
            with self._restart_lock:
                self.session.detach_task(task_id)
                self._detach_t[task_id] = time.monotonic()
                if reclaimed:
                    # the slot is still donated capacity: future
                    # retries stay gated on arbiter free slots
                    self._donated.add(task_id)
                    self.arbiter.reclaims -= 1
                    self._jrec("donated", task=task_id)
            self._jrec("detach", task=task_id)
        for h in handles:
            threading.Thread(target=self.provisioner.stop_container,
                             args=(h,), name=f"resize-drain-{h.role}",
                             daemon=True).start()

    # --------------------------------------------------------------- monitor
    def monitor(self) -> JobStatus:
        """The driver hot loop — reference monitor:633-728 and its exit
        conditions: timeout, client signal, heartbeat expiry, registration
        timeout, startup failure, runtime unhealthy, DAG stall, completion."""
        interval = self.conf.get_int(keys.AM_MONITOR_INTERVAL_MS, 200) / 1000
        timeout_ms = self.conf.get_int(keys.APPLICATION_TIMEOUT_MS, 0)
        reg_timeout_ms = self.conf.get_int(keys.AM_REGISTRATION_TIMEOUT_MS, 900000)
        hb_interval_ms = self.conf.get_int(keys.TASK_HEARTBEAT_INTERVAL_MS, 1000)
        max_missed = max(3, self.conf.get_int(keys.TASK_MAX_MISSED_HEARTBEATS, 25))
        hb_expiry_s = hb_interval_ms * max_missed / 1000

        while not self._stop_requested.is_set():
            now = time.time()

            # 1. application timeout
            if timeout_ms > 0 and now_ms() - self._start_ms > timeout_ms:
                self.session.kill_all(f"application timed out after {timeout_ms}ms")
                return JobStatus.KILLED

            # 2. heartbeat expiry (reference onTaskDeemedDead:1229-1236).
            # A task whose executor already reported its result has stopped
            # heartbeating legitimately — skip it even if an in-flight
            # heartbeat RPC re-inserted it after the unregister (the
            # completion-notification race, AM:1075-1087)
            for task_id, last in list(self.heartbeats.items()):
                task = self.session.get_task_by_id(task_id)
                if task is None or task.status.is_terminal() or task.exit_code is not None:
                    continue
                if task_id in self.session.detached:
                    # a detached slot's zombie executor may still beat on
                    # its way down; it is no longer liveness-tracked and
                    # its silence must not fail the job
                    self.heartbeats.pop(task_id, None)
                    continue
                if now - last > hb_expiry_s:
                    with self._restart_lock:
                        # re-check under the lock: a concurrent container-
                        # completion restart may have just relaunched this
                        # task (popping its heartbeat entry on the watcher
                        # thread) — proceeding on the stale read would
                        # kill the fresh attempt and double-spend the
                        # restart budget
                        last = self.heartbeats.get(task_id)
                        if last is None or now - last <= hb_expiry_s:
                            continue
                        msg = (f"task {task_id} missed {max_missed} "
                               "heartbeats; deemed dead")
                        log.error(msg)
                        with self._tt_lock:
                            self._hb_expired_count += 1
                        # a hung executor is a restartable failure, same
                        # as a crashed one: route it through the per-task
                        # budget BEFORE failing the whole job. Popping the
                        # handle under the lock makes the dying
                        # container's completion callback read as
                        # superseded (it must not burn a second restart or
                        # fail the job the new attempt is serving) — that
                        # also makes the same-task watcher path inert, so
                        # the kill itself can run OUTSIDE the lock: a
                        # SIGTERM-ignoring hung process costs its own 5s
                        # wait, not a stall of every other task's
                        # completion handling.
                        old = self._handles.pop(task_id, None)
                        self.heartbeats.pop(task_id, None)
                        self._driver_stops.add(task_id)
                    # stop BEFORE launching the replacement — the hung
                    # process still holds the device; a replacement racing
                    # it to chip init would exit device-busy and burn the
                    # budget on the collision
                    if old is not None:
                        self.provisioner.stop_container(old)
                    with self._restart_lock:
                        # the expiry IS the drain completing: an ADOPTED
                        # task's executor exits on the group SIGTERM
                        # without a watcher or a result RPC, so expiry is
                        # the only signal the driver gets. A scale-down
                        # victim parks and a donation's slot frees,
                        # budget-free, instead of burning a restart unit
                        # relaunching what was just drained (a stale
                        # donation ledger would also wedge every future
                        # scale-up at "awaiting_donation").
                        if self._discharge_scale_down(task_id):
                            continue
                        if self._discharge_donation(task_id):
                            continue
                    restarted = (
                        not self._stop_requested.is_set()
                        and self._try_restart_task(
                            task_id, c.EXIT_KILLED,
                            cause=f"missed {max_missed} heartbeats")
                    )
                    if restarted:
                        continue
                    # an autoscaled replica lost past its budget parks
                    # (the controller's floor rule relaunches it) —
                    # one bad replica must not fail the tenant pool
                    if self._park_failed_replica(task_id, cause=msg):
                        continue
                    # budget spent (or none configured): an elastic job
                    # re-forms the gang from the survivors instead of
                    # dying — worker loss becomes a latency cost
                    if (self._elastic_candidate(task_id)
                            and self._resize_down(task_id, cause=msg)):
                        continue
                    # record the heartbeat reason before the kill
                    # cascades into completion callbacks with a generic
                    # exit-code message. The trace terminal is the expiry
                    # itself — the dying container's later completion
                    # finds the trace already sealed
                    self._seal_task_trace(task_id, "heartbeat_expired",
                                          reason=msg)
                    self.session._fail(msg)
                    self.session.on_task_completed(
                        task.name, task.index, c.EXIT_KILLED)
                    self._jrec("terminal", task=task_id,
                               status=task.status.value,
                               exit_code=c.EXIT_KILLED)

            # 2b. straggler action: a worker whose step p50 lags the
            # gang median beyond the configured factor is restarted
            # through the normal budget (docs/training-robustness.md)
            self._check_stragglers(now)

            # 2c. elastic scale-up: retry detached slots whose timer
            # elapsed (capacity may have returned)
            if self._elastic:
                self._try_rescale_up()

            # 2d. seeded chaos (TONY_TEST_DRIVER_*): random container
            # kills and the one-shot step-triggered preemption
            self._chaos_tick()

            # 3. registration timeout (reference :1314-1334)
            for task_id, launched in list(self._launch_ms.items()):
                task = self.session.get_task_by_id(task_id)
                if task is None or task.status.is_terminal():
                    continue
                if (
                    task.status == TaskStatus.ALLOCATED
                    and now_ms() - launched > reg_timeout_ms
                ):
                    reg_msg = (f"task {task_id} failed to register within "
                               f"{reg_timeout_ms}ms")
                    # elastic: capacity that launches but never answers
                    # (half-dead host) detaches like any other loss
                    if (self._elastic_candidate(task_id)
                            and self._resize_down(task_id, cause=reg_msg)):
                        self._launch_ms.pop(task_id, None)
                        continue
                    self.session._fail(reg_msg)

            # 4. runtime health (gang allocation deadlock breaker)
            if not self.runtime_driver.is_healthy(self.conf):
                self.session._fail("runtime reported unhealthy (allocation timeout)")

            # 5. DAG stall: dependencies failed, dependents can never run
            if self.scheduler and self.scheduler.unscheduled_roles():
                tracked_done = all(
                    t.status.is_terminal()
                    for t in self.session.tracked_tasks()
                    if t.name not in self.scheduler.unscheduled_roles()
                )
                if tracked_done and self.session.status != JobStatus.FAILED:
                    self.session._fail(
                        "roles "
                        + ",".join(self.scheduler.unscheduled_roles())
                        + " blocked by failed dependencies"
                    )

            status = self.session.update_status()
            if status.is_terminal():
                return status
            time.sleep(interval)
        return self.session.update_status()

    def _kill_task(self, task_id: str) -> None:
        handle = self._handles.get(task_id)
        if handle is not None:
            self._driver_stops.add(task_id)
            self.provisioner.stop_container(handle)

    # ------------------------------------------------- straggler action
    def _pushed_metric(self, task_id: str, name: str) -> float | None:
        for entry in self.metrics.get(task_id, []):
            if entry.get("name") == name and isinstance(
                    entry.get("value"), (int, float)):
                return float(entry["value"])
        return None

    def _check_stragglers(self, now: float) -> None:
        """Act on the PR 5 skew telemetry: per role, compare each RUNNING
        task's pushed step-time p50 against the role median; a task slow
        beyond ``tony.train.straggler-restart-factor`` for
        ``straggler-grace-checks`` consecutive checks gets a budget-
        charged restart through the normal _try_restart_task path (its
        replacement lands on fresh capacity / a fresh process — the
        standard cure for a degraded host). Chief excluded: restarting
        rank 0 would tear down the rendezvous for everyone. 0 disables
        (observation-only, the PR 5 behavior)."""
        if self._straggler_factor <= 1.0 or self._stop_requested.is_set():
            return
        if now - self._straggler_check_t < 2.0:   # push cadence is ~5s;
            return                                 # checking faster is noise
        self._straggler_check_t = now
        from .metrics import STEP_TIME_P50_S

        metric = f"max_{STEP_TIME_P50_S}"
        for role in self.session.role_specs:
            p50s: dict[str, float] = {}
            for t in self.session.active_tasks():
                if t.name != role or t.status != TaskStatus.RUNNING:
                    continue
                v = self._pushed_metric(t.task_id, metric)
                if v is not None and v > 0:
                    p50s[t.task_id] = v
            if len(p50s) < 2:
                continue
            median = float(statistics.median(p50s.values()))
            if median <= 0:
                continue
            for task_id, p50 in p50s.items():
                name, _, idx = task_id.partition(":")
                if p50 <= self._straggler_factor * median:
                    self._straggler_strikes.pop(task_id, None)
                    continue
                if self.session.is_chief(name, int(idx)):
                    continue
                strikes = self._straggler_strikes.get(task_id, 0) + 1
                self._straggler_strikes[task_id] = strikes
                if strikes < self._straggler_grace:
                    continue
                spec = self.session.role_specs.get(name)
                used = self._restarts.get(task_id, 0)
                if spec is None or used >= spec.max_restarts:
                    continue    # no budget left: tolerate the laggard
                cause = (f"straggler: step p50 {p50:.3f}s vs role median "
                         f"{median:.3f}s (factor {self._straggler_factor})")
                # the whole stop+restart runs under the restart lock so a
                # concurrent container-exit restart can't interleave and
                # strand a stopped task (rare path; the up-to-5s stop
                # wait is acceptable here, unlike the hot expiry loop)
                with self._restart_lock:
                    used = self._restarts.get(task_id, 0)
                    if used >= spec.max_restarts:
                        continue
                    old = self._handles.pop(task_id, None)
                    self.heartbeats.pop(task_id, None)
                    self._driver_stops.add(task_id)
                    # stale quantiles must not condemn the replacement
                    self.metrics.pop(task_id, None)
                    self._straggler_strikes.pop(task_id, None)
                    if old is not None:
                        self.provisioner.stop_container(old)
                    self._try_restart_task(task_id, c.EXIT_KILLED,
                                           cause=cause)
                return      # at most one straggler restart per check:
                #             the median moves once a member leaves

    # --------------------------------------------------- driver chaos
    def _chaos_tick(self) -> None:
        """Seeded fault injection, one decision per monitor tick
        (TONY_TEST_DRIVER_*, constants.py): random SIGKILL of a running
        container, and a one-shot preemption drain once the gang's max
        observed training step reaches the trigger."""
        if self._stop_requested.is_set():
            return
        from .metrics import TRAIN_STEP

        if self._chaos_kill_rate and (
                self._chaos_rng.random() < self._chaos_kill_rate):
            with self._restart_lock:
                live = [t.task_id for t in self.session.active_tasks()
                        if t.status == TaskStatus.RUNNING
                        and t.task_id in self._handles
                        and t.task_id not in self._resizes]
                victim = (self._chaos_rng.choice(sorted(live))
                          if live else None)
                handle = self._handles.get(victim) if victim else None
            if handle is not None:
                log.warning("chaos: SIGKILLing %s (%s)", victim,
                            handle.container_id)
                self.provisioner.kill_container(handle)
        if self._chaos_sigkill_at and not self._chaos_sigkill_fired:
            steps = [self._pushed_metric(t.task_id, f"max_{TRAIN_STEP}")
                     for t in self.session.active_tasks()]
            top = max((s for s in steps if s is not None), default=0)
            if top >= self._chaos_sigkill_at:
                self._chaos_sigkill_fired = True
                import signal as _signal

                log.error("chaos: driver SIGKILLing ITSELF at observed "
                          "step %d — recover with `tony-tpu driver "
                          "--recover --job-dir %s`", int(top), self.job_dir)
                # a real SIGKILL, not os._exit: the signal path is what
                # production sees, and nothing below may run (no stop(),
                # no container teardown — that asymmetry is the point)
                os.kill(os.getpid(), _signal.SIGKILL)
        if (self._chaos_preempt_at and not self._chaos_preempt_fired):
            steps = [self._pushed_metric(t.task_id, f"max_{TRAIN_STEP}")
                     for t in self.session.active_tasks()]
            top = max((s for s in steps if s is not None), default=0)
            if top >= self._chaos_preempt_at:
                live = sorted(
                    t.task_id for t in self.session.active_tasks()
                    if t.status == TaskStatus.RUNNING
                    and t.task_id in self._handles)
                if live:
                    victim = self._chaos_rng.choice(live)
                    self._chaos_preempt_fired = True
                    log.warning("chaos: preempting %s at observed step %d",
                                victim, int(top))
                    self.preempt_task(victim)

    # ------------------------------------------------- on-demand profiling
    def request_profile(self, task_id: str, seconds: float = 5.0) -> bool:
        """Queue a profiler-capture command for ``task_id``; it rides the
        task's next heartbeat response (the executor then writes the
        ``$TONY_STEP_LOG.profile`` flag file the training child's
        StepTimer polls). Returns False for unknown/terminal tasks. A
        second request before the first is picked up replaces it —
        heartbeats arrive every ~1s, so queueing depth would only let
        stale captures pile up."""
        seconds = float(seconds)
        if not 0 < seconds <= 120:
            raise ValueError("seconds must be in (0, 120]")
        task = self.session.get_task_by_id(task_id)
        # NEW/REQUESTED tasks have no container, hence no heartbeat to
        # ride: queueing would park the command forever (or fire it at
        # whatever attempt eventually launches, long after the operator
        # asked) — treat them like unknown tasks
        if (task is None or task.status.is_terminal()
                or task.status in (TaskStatus.NEW, TaskStatus.REQUESTED)):
            return False
        with self._profile_lock:
            self._profile_cmds[task_id] = {"seconds": seconds}
        log.info("queued %gs profile capture for %s", seconds, task_id)
        return True

    def take_profile_command(self, task_id: str) -> dict | None:
        """One-shot drain of a pending profile command (heartbeat path)."""
        with self._profile_lock:
            return self._profile_cmds.pop(task_id, None)

    # ------------------------------------------------- control-plane recovery
    @classmethod
    def recover(cls, job_dir: str, provisioner: Provisioner | None = None,
                app_id: str = "",
                conf_overrides: dict | None = None) -> "Driver":
        """Build a replacement driver from a dead one's journal — the
        reproduction of YARN AM restart with
        ``keep-containers-across-application-attempts``: replay
        ``driver.journal.jsonl``, rebind RPC (the journaled port when
        still free), bump ``driver_generation``, and RE-ADOPT the live
        tasks — surviving executors' heartbeats re-attach by task id +
        attempt, dead-while-orphaned tasks fall to the normal heartbeat
        expiry path and relaunch under the journaled restart budget.
        ``run()`` afterwards behaves exactly like a first driver's: it
        rewrites driver.json (so outage-riding executors, warm-pool
        standbys, and router discovery re-resolve the new endpoint) and
        monitors to the job's terminal state."""
        from .events.driver_journal import rewrite_journal

        job_path = Path(job_dir)
        journal_path = job_path / c.DRIVER_JOURNAL_FILE
        state = load_state(journal_path)
        if state is None or not state.app_id:
            raise RuntimeError(
                f"no recoverable control-plane journal in {job_dir} "
                f"({journal_path.name} missing or without a meta record)")
        if app_id and app_id != state.app_id:
            raise RuntimeError(
                f"journal belongs to {state.app_id}, not {app_id}")
        conf = TonyConf.from_final(str(job_dir))
        for k, v in (conf_overrides or {}).items():
            conf.set(k, v)
        driver = cls(conf, app_id=state.app_id, job_dir=str(job_dir),
                     token=state.token, provisioner=provisioner,
                     rpc_port=state.rpc_port)
        driver._restore(state)
        # compact the journal down to the restored state BEFORE prepare()
        # re-opens it for appends: one file must not accrete every
        # incarnation's event stream. tmp+rename — a crash right here
        # leaves the previous journal intact.
        try:
            rewrite_journal(journal_path, state)
        except OSError:
            log.exception("journal compaction failed; recovering off the "
                          "uncompacted file")
        return driver

    def _restore(self, state: DriverState) -> None:
        """Adopt a journaled control-plane state wholesale (no locks:
        runs before any thread exists). Live tasks get re-adopted
        handles + fresh liveness clocks; tasks whose journaled pid is
        provably dead get an already-EXPIRED clock so the first monitor
        tick routes them through the normal budgeted-restart path."""
        from .warmpool import _pid_alive

        self._recovered_state = state
        self.driver_generation = state.driver_generation + 1
        self._recoveries = state.recoveries + 1
        self.session.restore_formation(
            session_id=state.session_id,
            gang_generation=state.gang_generation,
            detached=state.detached)
        self._preempts = set(state.preempts)
        self._preempt_cmds = set(state.preempt_cmds)
        self._rolls = set(state.rolls)
        self._resizes = set(state.resizes)
        # autoscaler/arbiter ledgers: parked slots stay the controller's,
        # mid-drain scale-downs/donations discharge on their completions,
        # donated slots stay gated on arbiter free capacity, and the
        # decision ledger's newest timestamp resumes the cooldown (a
        # recovered driver must not flap a decision its predecessor
        # just made)
        self._parked = set(state.parked)
        self._scale_downs = set(state.scale_downs)
        self._donations = dict(state.donations)
        self._donated = set(state.donated)
        if state.scale_ops:
            self._recovered_scale_t = max(
                float(op.get("t", 0.0) or 0.0) for op in state.scale_ops)
        now = time.time()
        hb_expiry_s = (self.conf.get_int(keys.TASK_HEARTBEAT_INTERVAL_MS,
                                         1000)
                       * max(3, self.conf.get_int(
                           keys.TASK_MAX_MISSED_HEARTBEATS, 25)) / 1000)
        adopt = getattr(self.provisioner, "adopt_container", None)
        for task_id, rec in sorted(state.tasks.items()):
            task = self.session.get_task_by_id(task_id)
            if task is None:
                log.warning("journaled task %s no longer in the config; "
                            "skipping", task_id)
                continue
            self._attempts[task_id] = rec.attempt
            if rec.restarts:
                self._restarts[task_id] = rec.restarts
            if rec.terminal:
                task.status = TaskStatus(rec.status)
                task.exit_code = rec.exit_code
                continue
            if rec.attempt == 0:
                continue        # never launched: scheduling covers it
            task.host = rec.host
            task.container_id = rec.container_id
            if rec.log_path:
                task.url = rec.log_path
            if task_id in state.detached:
                # a detached slot stays detached; the rescale timer
                # re-arms so capacity retries resume on schedule —
                # except autoscaler-PARKED slots, which only a scale-up
                # decision relaunches
                if task_id not in state.parked:
                    self._detach_t[task_id] = time.monotonic()
                continue
            if rec.registered:
                self.session.register_task(task_id, rec.reg_host,
                                           rec.reg_port)
                task.status = TaskStatus.RUNNING
                if rec.ports:
                    try:
                        self.session.set_task_ports(task_id, rec.ports)
                    except ValueError:
                        log.warning("journaled ports of %s malformed; "
                                    "dropped", task_id)
            else:
                task.status = TaskStatus.ALLOCATED
                # re-arm the registration timeout for the new incarnation
                self._launch_ms[task_id] = now_ms()
            # re-adopted handle: pid-identified, no Popen. The executor's
            # own register_execution_result is its authoritative
            # completion (on_task_result); a silently dead orphan is
            # caught by heartbeat expiry below.
            if callable(adopt):
                handle = self.provisioner.adopt_container(
                    container_id=rec.container_id or f"readopted_{task_id}",
                    host=rec.host or "127.0.0.1",
                    role=task.name, index=task.index, pid=rec.pid,
                    log_path=rec.log_path)
            else:
                handle = ContainerHandle(
                    container_id=rec.container_id or f"readopted_{task_id}",
                    host=rec.host or "127.0.0.1",
                    role=task.name, index=task.index,
                    extra={"adopted": True, "pid": rec.pid,
                           "log_path": rec.log_path})
            self._handles[task_id] = handle
            pid_live = rec.pid <= 0 or _pid_alive(rec.pid)
            if pid_live:
                # optimistic re-adoption: the liveness clock starts NOW;
                # a survivor's next heartbeat re-attaches it, a zombie
                # that never beats expires on the normal budget path
                self.heartbeats[task_id] = now
                self._readopted += 1
                with self._tt_lock:
                    self._reg_t[task_id] = time.monotonic()
                    self._attempt_wall[task_id] = rec.launch_t
                self._trace_mark(task_id, "readopted",
                                 attempt=rec.attempt,
                                 driver_generation=self.driver_generation,
                                 **({"pid": rec.pid} if rec.pid else {}))
                log.info("re-adopted %s (attempt %d%s)", task_id,
                         rec.attempt,
                         f", pid {rec.pid}" if rec.pid else "")
            else:
                # provably dead while orphaned: pre-expire its clock so
                # the first monitor tick relaunches it under the
                # journaled budget instead of waiting a full window
                self.heartbeats[task_id] = now - 10 * hb_expiry_s
                with self._tt_lock:
                    self._reg_t[task_id] = time.monotonic()
                log.warning("journaled pid %d of %s is dead; routing "
                            "through the expiry/restart path", rec.pid,
                            task_id)
        # a scale-down journaled but not yet drained when the old driver
        # died must be RE-ACTUATED: the re-adopted replica keeps serving
        # and heartbeating, so neither completion nor expiry would ever
        # discharge the ledger — the journaled "down" decision would
        # silently never take effect (and `draining` would offset the
        # control law's n_running for the rest of the job)
        for task_id in sorted(self._scale_downs):
            handle = self._handles.get(task_id)
            if handle is None:
                continue
            log.warning("resuming interrupted scale-down drain of %s",
                        task_id)
            threading.Thread(target=self.provisioner.stop_container,
                             args=(handle,),
                             name=f"scale-down-resume-{task_id}",
                             daemon=True).start()
        log.warning("recovered control plane of %s as driver generation "
                    "%d: %d task(s) re-adopted, %d restart unit(s) "
                    "already spent", self.app_id, self.driver_generation,
                    self._readopted, sum(self._restarts.values()))

    # ----------------------------------------------------------------- retry
    def reset(self) -> None:
        """Stop everything, rebuild the session with session_id+1 —
        reference reset:611-627. Provisioners that can re-discover capacity
        (a recreated spot TPU slice has new host addresses) refresh here."""
        self.provisioner.stop_all()
        # the old attempt's traces must not leak into the new session's
        # registry: seal whatever the completion callbacks haven't
        self._seal_remaining_traces()
        refresh = getattr(self.provisioner, "refresh", None)
        if callable(refresh):
            try:
                refresh()
            except Exception:
                log.exception("provisioner refresh failed; keeping old hosts")
        old = self.session
        self.session = Session(self.conf, session_id=old.session_id + 1)
        self.runtime_driver = self._runtime.driver_adapter()
        self.runtime_driver.set_session(self.session)
        # a whole-job retry starts from scratch: the old session's
        # journaled launches describe containers stop_all just killed,
        # and recovering THEM would resurrect a formation that no longer
        # exists — truncate and re-stamp the meta. _attempts stays: the
        # fence must keep refusing the previous session's zombies.
        self._recovered_state = None
        if self._journal is not None:
            self._journal.close()
            try:
                (self.job_dir / c.DRIVER_JOURNAL_FILE).write_text("")
            except OSError:
                log.exception("could not truncate the driver journal")
            self._journal = DriverJournal(
                self.job_dir / c.DRIVER_JOURNAL_FILE)
            self._jrec("meta", app_id=self.app_id, token=self.token,
                       session_id=self.session.session_id,
                       rpc_port=self.rpc_server.port,
                       driver_generation=self.driver_generation)
        self.heartbeats.clear()
        self._handles.clear()
        self._launch_ms.clear()
        self._restarts.clear()
        self._rolls.clear()
        self._preempts.clear()
        self._preempt_cmds.clear()
        self._resizes.clear()
        self._detach_t.clear()
        self._driver_stops.clear()
        self._straggler_strikes.clear()
        self.metrics.clear()
        # autoscaler/arbiter state follows the session: re-point the
        # arbiter at the fresh task table and re-park the slots above
        # the autoscale floor (journaled like a fresh prepare)
        self._scale_downs.clear()
        self._donations.clear()
        self._donation_reasons.clear()
        self._donated.clear()
        self._parked.clear()
        self.arbiter.session = self.session
        if self._autoscale_enabled and self._autoscale_role:
            n_min = max(0, self.conf.get_int(keys.AUTOSCALE_MIN, 1))
            for task in self.session.tasks.get(self._autoscale_role, []):
                if task.index >= n_min:
                    self.session.detach_task(task.task_id)
                    self._parked.add(task.task_id)
        if (self._autoscale_enabled and self._router_role
                and float(self.conf.get(keys.AUTOSCALE_ROUTER_RELAY_SLO,
                                        0) or 0) > 0):
            r_min = max(0, self.conf.get_int(keys.AUTOSCALE_ROUTER_MIN,
                                             1))
            for task in self.session.tasks.get(self._router_role, []):
                if task.index >= r_min:
                    self.session.detach_task(task.task_id)
                    self._parked.add(task.task_id)
                    self._jrec("detach", task=task.task_id)
                    self._jrec("park", task=task.task_id)

    # ------------------------------------------------------------------ stop
    def stop(self) -> None:
        """Reference stop:739-781: stop containers, wait briefly for the
        client's finish signal so it can read terminal state, then tear down."""
        status = self.session.status
        if self._autoscale_runner is not None:
            self._autoscale_runner.shutdown()
        if self._metrics_hub is not None:
            self._metrics_hub.stop()
        self.provisioner.stop_all()
        # reap the warm pool AFTER the containers: an adopted child dies
        # with its executor (control-pipe EOF), and idle standbys must
        # not outlive the job — reap() signals same-host pids and removes
        # the pool dir, which shared-FS standbys on other hosts notice
        # and self-exit on. Only the default per-job pool is reaped: an
        # explicit tony.warmpool.dir is a HOST-level pool the operator
        # shares across submits, and this job does not own its standbys.
        if self._warm_pool is not None:
            try:
                if Path(self._warm_pool.dir).resolve().is_relative_to(
                        self.job_dir.resolve()):
                    self._warm_pool.reap()
            except Exception:
                log.exception("warm pool reap failed")
        self._seal_remaining_traces()
        if self.events:
            failed = sum(
                1 for t in self.session.all_tasks()
                if t.status in (TaskStatus.FAILED, TaskStatus.KILLED)
            )
            self.events.emit(
                application_finished(
                    self.app_id, status.value, failed, self.session.failure_message
                )
            )
        # grace window for the client to pull final state + ack
        self.client_signal.wait(timeout=10)
        if self.events:
            self.events.stop(status.value)
        if self._task_trace_writer is not None:
            self._task_trace_writer.close()
        if self._journal is not None:
            self._journal.close()
        if self._metrics_httpd is not None:
            self._metrics_httpd.shutdown()
            self._metrics_httpd.server_close()
        self.rpc_server.stop()
        # release provisioner-owned capacity (driver-created TPU slices) —
        # after the client ack so a slow delete never delays terminal state
        try:
            self.provisioner.teardown()
        except Exception:
            log.exception("provisioner teardown failed")


def main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s driver %(name)s: %(message)s",
    )
    parser = argparse.ArgumentParser(description="tony-tpu job driver")
    parser.add_argument("--job-dir", required=True)
    parser.add_argument("--app-id", default="")
    parser.add_argument(
        "--recover", action="store_true",
        help="replay <job-dir>/driver.journal.jsonl and re-adopt the "
             "dead driver's live tasks instead of starting a fresh job "
             "(docs/training-robustness.md 'Control-plane recovery'); "
             "--app-id is then optional and only cross-checked")
    parser.add_argument(
        "--no-autoscale", action="store_true",
        help="run with the closed-loop autoscaler disabled even when "
             "tony.autoscale.enabled is set (operator override for "
             "incident debugging; docs/autoscaling.md)")
    parser.add_argument(
        "--autoscale-router-url", default="",
        help="fleet-router /stats URL merged into the autoscale "
             "controller's telemetry view (overrides "
             "tony.autoscale.router-stats-url)")
    args = parser.parse_args(argv)
    if not args.recover and not args.app_id:
        parser.error("--app-id is required (unless --recover)")

    # fault injection: driver crash mid-run (reference TEST_AM_CRASH,
    # ApplicationMaster.java:382-393) — handled after first task launch via env
    conf = TonyConf.from_final(args.job_dir)
    token = os.environ.get(c.ENV_TOKEN, "")

    # a killed driver must take its containers with it: executors run in
    # their own process groups (so the driver's own group kill can't reach
    # them) — mirror the reference AM shutdown hook stopping containers.
    # Handlers are registered BEFORE Driver construction (via a holder) so
    # a kill arriving right after the provisioner materialized a TPU slice
    # still releases it; the only uncovered window is a signal mid-slice-
    # creation inside the constructor itself.
    import signal as _signal

    holder: dict = {}

    def _teardown(signum):
        # containers first, then owned capacity — in SEPARATE try blocks so
        # a failure reaping processes can't skip the slice release (a
        # killed job leaking a billable TPU slice is the worse outcome).
        # `provisioner` is registered before acquisition even begins, so a
        # kill during the minutes-long await-READY poll still deletes the
        # slice it created; `driver` exists only once construction is done.
        try:
            if holder.get("driver") is not None:
                holder["driver"].provisioner.stop_all()
        except Exception:
            log.exception("stop_all on signal failed")
        try:
            if holder.get("provisioner") is not None:
                holder["provisioner"].teardown()
        except Exception:
            log.exception("teardown on signal failed")
        os._exit(128 + signum)

    def _on_term(signum, frame):
        # do the actual teardown on a helper thread: stop_all takes the
        # provisioner lock, which the interrupted main thread may hold —
        # blocking inside the handler would self-deadlock; returning lets
        # the main thread release it
        log.warning("signal %d: stopping all containers and exiting", signum)
        threading.Thread(target=_teardown, args=(signum,), daemon=True).start()

    _signal.signal(_signal.SIGTERM, _on_term)
    _signal.signal(_signal.SIGINT, _on_term)

    prov = create_provisioner(
        conf, on_constructing=lambda p: holder.__setitem__("provisioner", p)
    )
    holder["provisioner"] = prov  # non-lifecycle kinds never call back
    overrides: dict = {}
    if args.no_autoscale:
        overrides[keys.AUTOSCALE_ENABLED] = False
    if args.autoscale_router_url:
        overrides[keys.AUTOSCALE_ROUTER_STATS_URL] = \
            args.autoscale_router_url
    if args.recover:
        # auth root + endpoint come from the journal, not the env — the
        # supervisor relaunching a dead driver may not hold the secret
        driver = Driver.recover(args.job_dir, provisioner=prov,
                                app_id=args.app_id,
                                conf_overrides=overrides)
    else:
        for k, v in overrides.items():
            conf.set(k, v)
        driver = Driver(conf, app_id=args.app_id, job_dir=args.job_dir,
                        token=token, provisioner=prov)
    holder["driver"] = driver

    if os.environ.get(c.TEST_DRIVER_CRASH):
        def _crash_later():
            time.sleep(float(os.environ[c.TEST_DRIVER_CRASH]))
            log.error("TEST_DRIVER_CRASH: exiting now")
            os._exit(3)
        threading.Thread(target=_crash_later, daemon=True).start()

    status = driver.run()
    log.info("driver exiting with job status %s", status.value)
    return 0 if status == JobStatus.SUCCEEDED else 1


if __name__ == "__main__":
    sys.exit(main())
