"""History portal (reference: tony-portal Play app)."""

from .server import HistoryIndex, serve_portal

__all__ = ["HistoryIndex", "serve_portal"]
