"""History portal: web UI + JSON API over the job-history directory.

Mirrors tony-portal (Play app): routes `/`, `/jobs/<id>`, `/config/<id>`,
`/logs/<id>` (tony-portal/conf/routes:1-5), metadata/config/event caches
(tony-portal/app/cache/CacheWrapper.java:28-76 — here a TTL dict), and the
mover/purger housekeeping threads (HistoryFileMover/HistoryFilePurger) run
in-process. Stdlib http.server: no web-framework dependency.

Observability additions (docs/observability.md): `/traces/<id>` renders a
per-request timeline from the job's ``requests.trace.jsonl`` (written by
``serve --trace-dir``, TTL-cached like the event stream), `/tasks/<id>`
renders the gang-launch waterfall from ``tasks.trace.jsonl`` (written by
the driver), `/requests/<id>` lists the job's MERGED cross-tier
distributed traces (every tier's ``*.trace.jsonl`` joined by trace_id)
with `/requests/<id>/<trace_id>` rendering one trace's waterfall,
`/profiles/<id>` lists and serves captured jax.profiler
xplane dumps (from serve's `/debug/profile` and the driver's
profile-command path), `/slo/<id>` renders the job's SLO dashboard
(burn/budget sparklines replayed offline from ``metrics.tsdb.jsonl``
through the same MetricsHub + SLOEngine the live driver runs), and
`/metrics` exposes the portal's own request counters/latency in
Prometheus text format through the same renderer the serve endpoint
uses.
"""

from __future__ import annotations

import hmac
import html
import json
import logging
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from urllib.parse import parse_qs, urlencode, urlparse

from ..conf import TonyConf, keys
from ..events.handler import read_events
from ..events.history import (
    SUFFIX,
    HistoryFileMover,
    HistoryFilePurger,
    parse_history_file_name,
)
from ..events.trace import (TASK_TRACE_FILE, TRACE_FILE, TraceCollector,
                            coverage_s, read_traces)
from ..observability import PROM_CONTENT_TYPE, Histogram, PromRenderer

log = logging.getLogger(__name__)

# session cookie the browser auth flow sets in exchange for ?token=
_COOKIE_NAME = "tony_portal_token"


class _TTLCache:
    """Guava-cache stand-in: bounded TTL memo (CacheWrapper.java:28-76)."""

    def __init__(self, ttl_s: float = 30.0, max_items: int = 256):
        self._ttl = ttl_s
        self._max = max_items
        self._data: dict = {}

    def get(self, key, loader):
        now = time.time()
        hit = self._data.get(key)
        if hit and now - hit[0] < self._ttl:
            return hit[1]
        value = loader()
        if len(self._data) >= self._max:
            oldest = min(self._data, key=lambda k: self._data[k][0])
            del self._data[oldest]
        self._data[key] = (now, value)
        return value


class HistoryIndex:
    def __init__(self, conf: TonyConf):
        self.intermediate = Path(str(conf.get(keys.HISTORY_INTERMEDIATE)))
        self.finished = Path(str(conf.get(keys.HISTORY_FINISHED)))
        self.staging = Path(str(conf.get(keys.STAGING_DIR)))
        self._meta_cache = _TTLCache(ttl_s=10)
        self._events_cache = _TTLCache(ttl_s=30)
        self._trace_cache = _TTLCache(ttl_s=30)
        self._task_trace_cache = _TTLCache(ttl_s=30)
        self._merged_cache = _TTLCache(ttl_s=30)
        self._slo_cache = _TTLCache(ttl_s=10)

    def _job_dirs(self):
        for root in (self.intermediate, self.finished):
            if not root.exists():
                continue
            for jhist in root.rglob("*" + SUFFIX):
                yield jhist.parent, jhist

    def jobs(self) -> list[dict]:
        def load():
            out = []
            for job_dir, jhist in self._job_dirs():
                meta = parse_history_file_name(jhist.name)
                if meta is None:
                    continue
                out.append({
                    "app_id": meta.app_id,
                    "user": meta.user,
                    "started_ms": meta.start_ms,
                    "completed_ms": meta.end_ms,
                    "status": meta.status or "RUNNING",
                })
            out.sort(key=lambda j: -j["started_ms"])
            return out

        return self._meta_cache.get("jobs", load)

    def _find_job_dir(self, app_id: str):
        for job_dir, jhist in self._job_dirs():
            if job_dir.name == app_id:
                return job_dir, jhist
        return None, None

    def events(self, app_id: str) -> list[dict] | None:
        def load():
            _, jhist = self._find_job_dir(app_id)
            if jhist is None:
                return None
            return [
                {"type": e.type.value, "timestamp": e.timestamp, **e.payload}
                for e in read_events(jhist)
            ]

        return self._events_cache.get(("events", app_id), load)

    def traces(self, app_id: str) -> list[dict] | None:
        """Parsed request-trace records (``requests.trace.jsonl``, written
        by ``serve --trace-dir``) from the job's directory — TTL-cached
        exactly like the event stream: the file grows while the server
        runs, so the portal re-parses at most once per TTL."""
        def load():
            job_dir, _ = self._find_job_dir(app_id)
            if job_dir is None:
                return None
            path = job_dir / TRACE_FILE
            if not path.exists():
                return None
            return read_traces(path)

        return self._trace_cache.get(("traces", app_id), load)

    def task_traces(self, app_id: str) -> list[dict] | None:
        """Parsed TASK lifecycle traces (``tasks.trace.jsonl``, written
        by the driver) — the gang-launch waterfall's data; TTL-cached
        like the request traces."""
        def load():
            job_dir, _ = self._find_job_dir(app_id)
            if job_dir is None:
                return None
            path = job_dir / TASK_TRACE_FILE
            if not path.exists():
                return None
            return read_traces(path)

        return self._task_trace_cache.get(("tasks", app_id), load)

    def merged_traces(self, app_id: str) -> dict | None:
        """Cross-tier DISTRIBUTED traces for the job: every
        ``*.trace.jsonl`` under the job directory (routers and replicas
        pointed at the same ``--trace-dir`` each write their own file;
        task traces excluded — different granularity) merged by trace_id
        through TraceCollector. None when the job has no request-trace
        files at all; TTL-cached like the flat trace list."""
        def load():
            job_dir, _ = self._find_job_dir(app_id)
            if job_dir is None:
                return None
            collector = TraceCollector()
            for path in sorted(job_dir.rglob("*.trace.jsonl")):
                if path.name == TASK_TRACE_FILE:
                    continue
                collector.add_file(path)
            if collector.files_read == 0:
                return None
            return collector.merged()

        return self._merged_cache.get(("requests", app_id), load)

    def config(self, app_id: str) -> dict | None:
        for root in (self.staging,):
            path = root / app_id / "tony-final.json"
            if path.exists():
                return json.loads(path.read_text())
        job_dir, _ = self._find_job_dir(app_id)
        if job_dir is not None and (job_dir / "tony-final.json").exists():
            return json.loads((job_dir / "tony-final.json").read_text())
        return None

    def logs(self, app_id: str) -> dict[str, str] | None:
        log_dir = self.staging / app_id / "logs"
        if not log_dir.exists():
            return None
        out = {}
        for p in sorted(log_dir.iterdir()):
            if p.is_dir():      # profiles/ subtree: listed on /profiles
                continue
            try:
                out[p.name] = p.read_text()[-20000:]
            except OSError:
                continue
        return out

    def _profile_roots(self, app_id: str) -> list[Path]:
        """Where captured xplane profiles live for this job: the history
        job dir's ``profiles/`` (``serve --trace-dir`` pointed at the
        history dir + /debug/profile) and the staging ``logs/profiles/``
        tree (training children, via the driver's profile command and
        the ``$TONY_STEP_LOG.profile`` flag contract)."""
        roots = []
        job_dir, _ = self._find_job_dir(app_id)
        if job_dir is not None:
            roots.append(job_dir / "profiles")
        roots.append(self.staging / app_id / "logs" / "profiles")
        return [r for r in roots if r.is_dir()]

    def profiles(self, app_id: str) -> list[dict] | None:
        """Captured profile files for the job page: one entry per file
        under either profile root (relative name, size, mtime). None
        when no captures exist — the route 404s instead of rendering an
        empty page for a job that was never profiled."""
        roots = self._profile_roots(app_id)
        if not roots:
            return None
        out = []
        for root in roots:
            for p in sorted(root.rglob("*")):
                if not p.is_file():
                    continue
                try:
                    st = p.stat()
                except OSError:
                    continue
                out.append({"name": str(p.relative_to(root)),
                            "bytes": st.st_size,
                            "mtime": int(st.st_mtime)})
        return out

    def slo(self, app_id: str) -> dict | None:
        """Offline SLO dashboard data: replay the job's persisted
        ``metrics.tsdb.jsonl`` into a fresh MetricsHub, evaluate the
        conf-declared objectives at the LAST retained timestamp (the
        portal has no live clock into the job), seed alert state from
        the driver journal's ``slo_alert`` records, and sample burn /
        budget curves across the retained span for the sparklines.
        None when the job never persisted a TSDB or declares no SLOs
        — the route 404s. TTL-cached like every other replayed file."""
        def load():
            from .. import constants as c
            from ..events.driver_journal import load_state
            from ..metricshub import TSDB_FILE, MetricsHub
            from ..slo import SLOEngine, slo_objectives_from_conf

            conf_dict = self.config(app_id)
            if conf_dict is None:
                return None
            job_dir, _ = self._find_job_dir(app_id)
            roots = [self.staging / app_id]
            if job_dir is not None:
                roots.append(job_dir)
            tsdb = next((r / TSDB_FILE for r in roots
                         if (r / TSDB_FILE).exists()), None)
            if tsdb is None:
                return None
            objectives = slo_objectives_from_conf(TonyConf(conf_dict))
            if not objectives:
                return None
            hub = MetricsHub(persist_dir=None,
                             retention_s=float("inf"), max_points=4096)
            hub.load(tsdb)
            times = list(hub.targets().values())
            if not times:
                return None
            now = max(times)
            initial: dict = {}
            for root in roots:
                jpath = root / c.DRIVER_JOURNAL_FILE
                if not jpath.exists():
                    continue
                try:
                    state = load_state(jpath)
                except Exception:
                    break
                if state is None:
                    break
                for key, entry in state.slo_alerts.items():
                    name, _, sev = key.rpartition(":")
                    initial[(name, sev)] = entry.get("state") == "firing"
                break
            engine = SLOEngine(hub, objectives, now_fn=lambda: now,
                               initial_alerts=initial)
            snap = engine.evaluate()
            # sparkline fodder: short-window burn + full-window budget
            # sampled across the retained span (hub rings, same math
            # the live engine runs)
            first = min((s.ring[0][0]
                         for s in hub._series.values() if s.ring),
                        default=now)
            n = 32
            span = max(now - first, 1e-9)
            ts = [first + span * i / (n - 1) for i in range(n)]
            for s_slo, slo in zip(snap["slos"], engine.objectives):
                short_w = slo.window_s / 60.0
                s_slo["spark_t"] = ts
                s_slo["spark_burn"] = [
                    engine.burn_rate(slo, short_w, t) for t in ts]
                budget = []
                for t in ts:
                    bad, total = engine._bad_total(slo, slo.window_s, t)
                    er = bad / total if total > 0 else 0.0
                    budget.append(1.0 - er / (1.0 - slo.target))
                s_slo["spark_budget"] = budget
            return {"app_id": app_id, "t": now, "eval": snap,
                    "alerts": engine.snapshot()["alerts"]}

        return self._slo_cache.get(("slo", app_id), load)

    def profile_file(self, app_id: str, rel: str) -> bytes | None:
        """One captured profile's bytes (the xplane proto TensorBoard's
        profile plugin / xprof loads). The resolved path must stay under
        a profile root — the relative name comes off the URL and must
        not become a directory-traversal read primitive."""
        for root in self._profile_roots(app_id):
            root_res = root.resolve()
            try:
                path = (root / rel).resolve()
            except OSError:
                continue
            if root_res not in path.parents:
                continue
            if path.is_file():
                try:
                    return path.read_bytes()
                except OSError:
                    continue
        return None


_PAGE = """<!doctype html><html><head><title>tony-tpu history</title>
<style>body{{font-family:monospace;margin:2em}}table{{border-collapse:collapse}}
td,th{{border:1px solid #999;padding:4px 10px;text-align:left}}
.SUCCEEDED{{color:green}}.FAILED{{color:red}}.KILLED{{color:orange}}</style>
</head><body><h2>tony-tpu job history</h2>{body}</body></html>"""


# sortable columns of the job index: query name -> job-dict key (the JS-free
# counterpart of the reference's DataTables index,
# tony-portal/app/views/index.scala.html)
_SORT_KEYS = {
    "job": "app_id", "user": "user", "started": "started_ms",
    "completed": "completed_ms", "status": "status",
}
_DEFAULT_PER_PAGE = 50


def sort_page_jobs(jobs: list[dict], qs: dict) -> tuple[list[dict], dict]:
    """Apply ?sort/?dir/?page/?per to the job list; returns (page, info)
    where info carries the resolved params + page count for link building."""
    sort = qs.get("sort", ["started"])[0]
    key = _SORT_KEYS.get(sort) or "started_ms"
    if key == "started_ms" and sort != "started":
        sort = "started"
    direction = qs.get("dir", [""])[0]
    if direction not in ("asc", "desc"):
        # newest-first is the natural default for timestamps, a-z for text
        direction = "desc" if key.endswith("_ms") else "asc"
    jobs = sorted(jobs, key=lambda j: (j[key] is None, j[key]),
                  reverse=direction == "desc")
    try:
        per = max(1, min(500, int(qs.get("per", [_DEFAULT_PER_PAGE])[0])))
    except ValueError:
        per = _DEFAULT_PER_PAGE
    pages = max(1, -(-len(jobs) // per))
    try:
        page = max(1, min(pages, int(qs.get("page", [1])[0])))
    except ValueError:
        page = 1
    info = {"sort": sort, "dir": direction, "page": page, "per": per,
            "pages": pages, "total": len(jobs)}
    return jobs[(page - 1) * per: page * per], info


def _jobs_html(jobs: list[dict], info: dict) -> str:
    def link(**over) -> str:
        params = {"sort": info["sort"], "dir": info["dir"],
                  "page": info["page"], "per": info["per"], **over}
        return "/?" + urlencode(params)

    def th(label: str, col: str) -> str:
        if info["sort"] == col:  # clicking the active column flips it
            mark = " ▾" if info["dir"] == "desc" else " ▴"
            nxt = "asc" if info["dir"] == "desc" else "desc"
        else:
            mark, nxt = "", "asc"
        return (f"<th><a href='{link(sort=col, dir=nxt, page=1)}'>"
                f"{label}{mark}</a></th>")

    rows = "".join(
        f"<tr><td><a href='/jobs/{html.escape(j['app_id'])}'>{html.escape(j['app_id'])}</a></td>"
        f"<td>{html.escape(j['user'])}</td>"
        f"<td>{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(j['started_ms']/1000))}</td>"
        f"<td class='{j['status']}'>{j['status']}</td>"
        f"<td><a href='/config/{j['app_id']}'>config</a> "
        f"<a href='/logs/{j['app_id']}'>logs</a></td></tr>"
        for j in jobs
    )
    pager = (
        f"<p>{info['total']} jobs — page {info['page']}/{info['pages']}"
        + (f" <a href='{link(page=info['page'] - 1)}'>&laquo; prev</a>"
           if info["page"] > 1 else "")
        + (f" <a href='{link(page=info['page'] + 1)}'>next &raquo;</a>"
           if info["page"] < info["pages"] else "")
        + "</p>"
    )
    return _PAGE.format(
        body="<table><tr>" + th("job", "job") + th("user", "user")
             + th("started", "started") + th("status", "status")
             + f"<th></th></tr>{rows}</table>" + pager
    )


def _job_detail_html(app_id: str, events: list[dict]) -> str:
    """Job page: event timeline + per-task metrics pulled from
    TASK_FINISHED payloads (reference: tony-portal JobEventPage rendering
    the jhist event array, metrics embedded per TaskFinished.avsc)."""
    ev_rows = []
    metric_rows = []
    for e in events:
        ts = time.strftime("%H:%M:%S", time.localtime(e["timestamp"] / 1000))
        detail = {k: v for k, v in e.items()
                  if k not in ("type", "timestamp", "metrics")}
        ev_rows.append(
            f"<tr><td>{ts}</td><td>{html.escape(e['type'])}</td>"
            f"<td>{html.escape(json.dumps(detail))}</td></tr>"
        )
        for m in e.get("metrics") or []:
            name = f"{e.get('job_name', '?')}:{e.get('task_index', '?')}"
            metric_rows.append(
                f"<tr><td>{html.escape(name)}</td>"
                f"<td>{html.escape(str(m.get('name')))}</td>"
                f"<td>{html.escape(str(m.get('value')))}</td></tr>"
            )
    body = (
        f"<h3>{html.escape(app_id)}</h3>"
        f"<p><a href='/'>all jobs</a> | "
        f"<a href='/config/{html.escape(app_id)}'>config</a>"
        f" | <a href='/logs/{html.escape(app_id)}'>logs</a>"
        f" | <a href='/traces/{html.escape(app_id)}'>requests</a>"
        f" | <a href='/requests/{html.escape(app_id)}'>traces</a>"
        f" | <a href='/tasks/{html.escape(app_id)}'>tasks</a>"
        f" | <a href='/profiles/{html.escape(app_id)}'>profiles</a></p>"
        "<h4>events</h4><table><tr><th>time</th><th>type</th><th>detail</th></tr>"
        + "".join(ev_rows) + "</table>"
    )
    if metric_rows:
        body += (
            "<h4>task metrics</h4>"
            "<table><tr><th>task</th><th>metric</th><th>value</th></tr>"
            + "".join(metric_rows) + "</table>"
        )
    return _PAGE.format(body=body)


# waterfall segment color, keyed by the span that ENDS the segment
_SEG_COLORS = {
    "admitted": "#b5b5b5",      # queue wait
    "prefill_done": "#7aa7d6",  # admission prefill dispatch
    "first_token": "#e0a86c",   # decode to the first observed token
    "finished": "#79b77a",      # decode to completion
    "cancelled": "#d98080", "expired": "#d98080",
    "shed": "#d98080", "failed": "#d98080",
}


def _request_timeline_html(app_id: str, traces: list[dict]) -> str:
    """Per-request waterfall over the trace JSONL: one row per request,
    phase durations from the monotonic spans, the bar scaled to the
    slowest request on the page (same table style as the job pages).
    Span timestamps are host-monotonic (docs/observability.md) — only
    differences are meaningful, so everything renders relative. Records
    whose spans are not [name, number] pairs are dropped, same contract
    as read_traces' torn-line skip: one malformed record must not 500
    every other request's timeline."""
    def well_formed(r):
        spans = r.get("spans")
        return (isinstance(spans, list) and spans and all(
            isinstance(s, (list, tuple)) and len(s) == 2
            and isinstance(s[0], str) and isinstance(s[1], (int, float))
            for s in spans))

    recs = [r for r in traces if isinstance(r, dict) and well_formed(r)]
    recs.sort(key=lambda r: r["spans"][0][1])
    t_max = max((r["spans"][-1][1] - r["spans"][0][1] for r in recs),
                default=0.0) or 1e-9

    def t_of(spans, name):
        return next((t for n, t in spans if n == name), None)

    rows = []
    for r in recs:
        spans, attrs = r["spans"], r.get("attrs", {})
        t0 = spans[0][1]
        e2e = spans[-1][1] - t0
        outcome = attrs.get("finish_reason", spans[-1][0])
        bar = ""
        for (pn, pt), (nn, nt) in zip(spans, spans[1:]):
            width = max(0.3, 100.0 * (nt - pt) / t_max)
            bar += (
                f"<div title='{html.escape(pn)}&rarr;{html.escape(nn)} "
                f"{nt - pt:.3f}s' style='display:inline-block;height:12px;"
                f"width:{width:.2f}%;background:"
                f"{_SEG_COLORS.get(nn, '#999')}'></div>")
        t_adm, t_ft = t_of(spans, "admitted"), t_of(spans, "first_token")
        fmt = lambda v: "" if v is None else f"{v:.3f}"
        # every record-sourced value is escaped: the trace file is data,
        # and anything that can append to the job dir writes it
        rows.append(
            f"<tr><td>{html.escape(str(r.get('id', '?')))}</td>"
            f"<td class='{html.escape(str(outcome))}'>"
            f"{html.escape(str(outcome))}</td>"
            f"<td>{html.escape(str(attrs.get('n_tokens', '')))}</td>"
            f"<td>{html.escape(str(attrs.get('prefix_hit_blocks', '')))}</td>"
            f"<td>{fmt(None if t_adm is None else t_adm - t0)}</td>"
            f"<td>{fmt(None if t_ft is None else t_ft - t0)}</td>"
            f"<td>{fmt(e2e)}</td>"
            f"<td style='min-width:240px'>{bar}</td></tr>")
    legend = " ".join(
        f"<span style='background:{c};padding:0 6px'>&nbsp;</span>"
        f"{html.escape(n)}"
        for n, c in (("queue", "#b5b5b5"), ("prefill", "#7aa7d6"),
                     ("to first token", "#e0a86c"), ("decode", "#79b77a"),
                     ("terminated early", "#d98080")))
    body = (
        f"<h3>{html.escape(app_id)} — request timeline</h3>"
        f"<p><a href='/'>all jobs</a> | "
        f"<a href='/jobs/{html.escape(app_id)}'>events</a></p>"
        f"<p>{len(recs)} requests — timestamps are host-monotonic; bars "
        f"scale to the slowest request ({t_max:.3f}s). {legend}</p>"
        "<table><tr><th>request</th><th>outcome</th><th>tokens</th>"
        "<th>prefix blocks</th><th>queue wait s</th><th>ttft s</th>"
        "<th>e2e s</th><th>timeline</th></tr>"
        + "".join(rows) + "</table>"
    )
    return _PAGE.format(body=body)


def _requests_list_html(app_id: str, traces: dict) -> str:
    """Distributed-trace index for one job: every merged cross-tier
    trace, slowest first, failures flagged — the triage entry point
    (docs/observability.md "Distributed tracing"). Each trace_id links
    to its waterfall page."""
    rows = []
    items = []
    for t in traces.values():
        if not t["spans"]:
            continue
        dur = (max(s["end"] for s in t["spans"])
               - min(s["start"] for s in t["spans"]))
        bad = any(s["terminal"] in ("failed", "shed", "expired")
                  for s in t["spans"])
        items.append((dur, bad, t))
    items.sort(key=lambda x: (-x[1], -x[0]))
    for dur, bad, t in items:
        tid = str(t["trace_id"])
        services = sorted({str(s.get("service") or "?")
                           for s in t["spans"]})
        status = "FAILED" if bad else "ok"
        rows.append(
            f"<tr><td><a href='/requests/{html.escape(app_id)}/"
            f"{html.escape(tid)}'>{html.escape(tid)}</a></td>"
            f"<td class='{'FAILED' if bad else 'SUCCEEDED'}'>{status}</td>"
            f"<td>{dur:.3f}</td><td>{len(t['spans'])}</td>"
            f"<td>{len(t['orphans'])}</td>"
            f"<td>{html.escape(', '.join(services))}</td></tr>")
    body = (
        f"<h3>{html.escape(app_id)} — distributed traces</h3>"
        f"<p><a href='/'>all jobs</a> | "
        f"<a href='/jobs/{html.escape(app_id)}'>events</a> | "
        f"<a href='/traces/{html.escape(app_id)}'>flat timeline</a></p>"
        f"<p>{len(rows)} merged traces — failed first, then slowest "
        "(spans merged across every tier's trace file by trace_id).</p>"
        "<table><tr><th>trace</th><th>status</th><th>wall s</th>"
        "<th>spans</th><th>orphans</th><th>tiers</th></tr>"
        + "".join(rows) + "</table>"
    )
    return _PAGE.format(body=body)


def _request_waterfall_html(app_id: str, trace: dict) -> str:
    """Cross-tier waterfall for ONE merged trace: a row per span (router
    relay legs, prefill leg, decode/recovered attempts), bars on the
    shared re-anchored wall timeline, segments colored by the lifecycle
    event that ends them — the HTML twin of events.trace.
    render_waterfall. Everything record-sourced is escaped: trace files
    are data, and anything that can append to the job dir writes them."""
    spans = trace["spans"]
    tid = str(trace["trace_id"])
    t0 = min((s["start"] for s in spans), default=0.0)
    t_max = max((s["end"] - t0 for s in spans), default=0.0) or 1e-9
    rows = []
    for s in spans:
        attrs = s.get("attrs") or {}
        svc = str(s.get("service") or "?")
        who = attrs.get("router") or attrs.get("replica") or ""
        label = svc + (f"[{who}]" if who else "")
        notes = []
        if attrs.get("recovered_from") is not None:
            notes.append(f"recovered from #{attrs['recovered_from']}")
        if s.get("reanchored_s"):
            notes.append(f"reanchored +{s['reanchored_s']:.3f}s")
        if s.get("terminal") is None:
            notes.append("UNSEALED")
        lead = 100.0 * (s["start"] - t0) / t_max
        bar = (f"<div style='display:inline-block;height:12px;"
               f"width:{lead:.2f}%'></div>") if lead > 0.01 else ""
        events = s.get("events") or []
        for (pn, pt), (nn, nt) in zip(events, events[1:]):
            width = max(0.3, 100.0 * (nt - pt) / t_max)
            bar += (
                f"<div title='{html.escape(str(pn))}&rarr;"
                f"{html.escape(str(nn))} {nt - pt:.3f}s' "
                f"style='display:inline-block;height:12px;"
                f"width:{width:.2f}%;background:"
                f"{_SEG_COLORS.get(nn, '#999')}'></div>")
        marks = ",".join(str(n) for n, _ in events)
        rows.append(
            f"<tr><td>{html.escape(label)}</td>"
            f"<td>{html.escape(str(s.get('id', '?')))}</td>"
            f"<td class='{html.escape(str(s.get('terminal')))}'>"
            f"{html.escape(str(s.get('terminal') or 'open'))}</td>"
            f"<td>{s['end'] - s['start']:.3f}</td>"
            f"<td style='min-width:280px'>{bar}</td>"
            f"<td>{html.escape(marks)}</td>"
            f"<td>{html.escape('; '.join(notes))}</td></tr>")
    cov = coverage_s(trace)
    orphan_note = (
        f"<p class='FAILED'>orphan spans (parent never wrote a record): "
        f"{html.escape(', '.join(str(o) for o in trace['orphans']))}</p>"
        if trace["orphans"] else "")
    body = (
        f"<h3>{html.escape(app_id)} — trace {html.escape(tid)}</h3>"
        f"<p><a href='/'>all jobs</a> | "
        f"<a href='/requests/{html.escape(app_id)}'>all traces</a> | "
        f"<a href='/jobs/{html.escape(app_id)}'>events</a></p>"
        f"<p>{len(spans)} spans across "
        f"{len({str(s.get('service') or '?') for s in spans})} tier(s); "
        f"{t_max:.3f}s wall, {cov:.3f}s covered by the span union. "
        "Bars share one re-anchored wall timeline; a child starting "
        "before its parent has been shifted (see the notes column).</p>"
        "<table><tr><th>tier</th><th>request</th><th>terminal</th>"
        "<th>span s</th><th>timeline</th><th>events</th><th>notes</th>"
        "</tr>" + "".join(rows) + "</table>" + orphan_note
    )
    return _PAGE.format(body=body)


# task-waterfall segment color, keyed by the span that ENDS the segment
# (observability.TaskTrace vocabulary)
_TASK_SEG_COLORS = {
    "allocated": "#b5b5b5",        # waiting for capacity
    "launched": "#9aa7b8",         # allocation -> container launch
    "registered": "#7aa7d6",       # launch -> worker registration
    "first_heartbeat": "#8fc1d9",  # registration -> liveness
    "running": "#c9d68a",          # gang barrier release
    "work_dir_ready": "#d6c97a",   # executor-side setup
    "child_spawned": "#e0a86c",    # user process up (cold spawn)
    "child_adopted": "#6cbfe0",    # user process up via warm-pool
    #                                adoption (the prepaid launch path —
    #                                attrs carry warm_pool hit/miss)
    "child_exited": "#c9a0d6",     # user process done, result in flight
    "finished": "#79b77a",
    "restarted": "#e0876c",
    "rolled": "#8fd0c9",           # deliberate budget-free relaunch
    "preempting": "#d6b35c",       # drain notice relayed
    "preempted": "#d6b35c",        # drained + budget-free relaunch
    "resized": "#9a7fd0",          # elastic gang re-formation
    "readopted": "#67c5a8",        # re-adopted by a RECOVERED driver
    #                                (control-plane recovery — the task
    #                                never stopped; attrs carry the new
    #                                driver_generation)
    "scaled_up": "#6fd0a0",        # autoscaler claimed this parked slot
    "scaled_down": "#5f9ea0",      # autoscaler drained + parked it
    "donated": "#d98fc4",          # batch worker's slot donated to the
    #                                interactive tier (arbiter preempt
    #                                drain; docs/autoscaling.md)
    "reclaimed": "#b4d98f",        # donated slot returned to batch
    "ckpt_prestaged": "#cfd98f",   # checkpoint pre-read before the
    #                                barrier (rescale placement)
    "failed": "#d98080", "killed": "#d98080",
    "heartbeat_expired": "#d98080",
}


def _task_timeline_html(app_id: str, traces: list[dict]) -> str:
    """Gang-launch waterfall: one row per task, phase segments between
    consecutive lifecycle spans, bars scaled to the slowest task. Built
    like the request timeline (same well-formedness contract — a torn or
    malformed record is dropped, never a 500); executor-shipped spans
    are wall-clock re-anchored by the driver, so the record's span list
    is sorted by timestamp before segmenting."""
    def well_formed(r):
        spans = r.get("spans")
        return (isinstance(spans, list) and spans and all(
            isinstance(s, (list, tuple)) and len(s) == 2
            and isinstance(s[0], str) and isinstance(s[1], (int, float))
            for s in spans))

    # terminal comes from RECORD order (the driver always seals last) —
    # an NTP-skewed executor span sorted past it must not relabel the
    # task; the sort is only for bar segmentation
    recs = [dict(r, spans=sorted(r["spans"], key=lambda s: s[1]),
                 terminal=r["spans"][-1][0])
            for r in traces if isinstance(r, dict) and well_formed(r)]

    def id_key(r):
        # "worker:10" must sort after "worker:9", not after "worker:1"
        role, _, idx = str(r.get("id", "")).partition(":")
        return (role, int(idx)) if idx.isdigit() else (role, -1, idx)

    recs.sort(key=lambda r: (id_key(r), r["spans"][0][1]))
    t0_all = min((r["spans"][0][1] for r in recs), default=0.0)
    t_max = max((r["spans"][-1][1] - t0_all for r in recs),
                default=0.0) or 1e-9

    def t_of(spans, name):
        return next((t for n, t in spans if n == name), None)

    rows = []
    for r in recs:
        spans, attrs = r["spans"], r.get("attrs", {})
        terminal = r["terminal"]
        restarts = attrs.get("restarts", "")
        # bars share one origin (the job's first request): the waterfall
        # shows gang SKEW, not just per-task phase splits
        lead = 100.0 * (spans[0][1] - t0_all) / t_max
        bar = (f"<div style='display:inline-block;height:12px;"
               f"width:{lead:.2f}%'></div>") if lead > 0.01 else ""
        for (pn, pt), (nn, nt) in zip(spans, spans[1:]):
            width = max(0.3, 100.0 * (nt - pt) / t_max)
            bar += (
                f"<div title='{html.escape(pn)}&rarr;{html.escape(nn)} "
                f"{nt - pt:.3f}s' style='display:inline-block;height:12px;"
                f"width:{width:.2f}%;background:"
                f"{_TASK_SEG_COLORS.get(nn, '#999')}'></div>")
        t_reg = t_of(spans, "registered")
        fmt = lambda v: "" if v is None else f"{v:.3f}"
        rows.append(
            f"<tr><td>{html.escape(str(r.get('id', '?')))}</td>"
            f"<td class='{html.escape(str(terminal))}'>"
            f"{html.escape(str(terminal))}</td>"
            f"<td>{html.escape(str(restarts))}</td>"
            f"<td>{fmt(None if t_reg is None else t_reg - spans[0][1])}</td>"
            f"<td>{fmt(spans[-1][1] - spans[0][1])}</td>"
            f"<td style='min-width:280px'>{bar}</td></tr>")
    legend = " ".join(
        f"<span style='background:{c};padding:0 6px'>&nbsp;</span>"
        f"{html.escape(n)}"
        for n, c in (("capacity", "#b5b5b5"), ("launch", "#9aa7b8"),
                     ("register", "#7aa7d6"), ("liveness", "#8fc1d9"),
                     ("barrier", "#c9d68a"), ("child up", "#e0a86c"),
                     ("adopted", "#6cbfe0"),
                     ("done", "#79b77a"), ("restart", "#e0876c"),
                     ("roll", "#8fd0c9"), ("preempt", "#d6b35c"),
                     ("resize", "#9a7fd0"), ("readopted", "#67c5a8"),
                     ("scale up", "#6fd0a0"), ("scale down", "#5f9ea0"),
                     ("donated", "#d98fc4"), ("reclaimed", "#b4d98f"),
                     ("dead", "#d98080")))
    body = (
        f"<h3>{html.escape(app_id)} — gang-launch waterfall</h3>"
        f"<p><a href='/'>all jobs</a> | "
        f"<a href='/jobs/{html.escape(app_id)}'>events</a> | "
        f"<a href='/traces/{html.escape(app_id)}'>requests</a></p>"
        f"<p>{len(recs)} tasks — timestamps are driver-host-monotonic; "
        f"bars share the job's first request as origin and scale to the "
        f"slowest task ({t_max:.3f}s). {legend}</p>"
        "<table><tr><th>task</th><th>terminal</th><th>restarts</th>"
        "<th>reg s</th><th>e2e s</th><th>timeline</th></tr>"
        + "".join(rows) + "</table>"
    )
    return _PAGE.format(body=body)


def _profiles_html(app_id: str, profiles: list[dict]) -> str:
    """Captured-profile listing: one row per xplane/artifact file with a
    download link; viewing instructions point at TensorBoard's profile
    plugin (docs/observability.md "Device timing & profiling")."""
    rows = "".join(
        f"<tr><td><a href='/profiles/{html.escape(app_id)}/"
        f"{html.escape(p['name'])}'>{html.escape(p['name'])}</a></td>"
        f"<td>{p['bytes']}</td>"
        f"<td>{time.strftime('%Y-%m-%d %H:%M:%S', time.localtime(p['mtime']))}"
        f"</td></tr>"
        for p in profiles
    )
    body = (
        f"<h3>{html.escape(app_id)} — captured profiles</h3>"
        f"<p><a href='/'>all jobs</a> | "
        f"<a href='/jobs/{html.escape(app_id)}'>events</a></p>"
        f"<p>{len(profiles)} files. View a capture with TensorBoard's "
        "profile plugin: download the directory structure and run "
        "<code>tensorboard --logdir &lt;capture dir&gt;</code> "
        "(see docs/observability.md).</p>"
        "<table><tr><th>file</th><th>bytes</th><th>captured</th></tr>"
        + rows + "</table>"
    )
    return _PAGE.format(body=body)


_SPARK_BLOCKS = "▁▂▃▄▅▆▇█"


def _sparkline(values: list[float]) -> str:
    """Unicode block sparkline, min..max normalized (flat series
    renders as the low block — no signal, no shape)."""
    if not values:
        return ""
    lo, hi = min(values), max(values)
    if hi - lo <= 1e-12:
        return _SPARK_BLOCKS[0] * len(values)
    top = len(_SPARK_BLOCKS) - 1
    return "".join(
        _SPARK_BLOCKS[int((v - lo) / (hi - lo) * top + 0.5)]
        for v in values)


def _slo_html(app_id: str, data: dict) -> str:
    """SLO dashboard: one card per objective — budget remaining, alert
    state per severity, burn rates per derived window, and the burn /
    budget sparklines over the TSDB's retained span (docs/observability.md
    "Metrics pipeline & SLO alerting")."""
    cards = []
    for s in data["eval"]["slos"]:
        budget = s["error_budget_remaining"]
        alerts = "".join(
            f"<td class='{'bad' if firing else 'ok'}'>{html.escape(sev)}: "
            f"{'FIRING' if firing else 'ok'}</td>"
            for sev, firing in sorted(s["alerts"].items()))
        burns = "".join(
            f"<tr><td>{html.escape(w)}s</td><td>{b:.3f}×</td></tr>"
            for w, b in sorted(s["burn_rates"].items(),
                               key=lambda kv: float(kv[0])))
        cards.append(
            f"<h3>{html.escape(s['name'])} "
            f"<small>({html.escape(s['objective'])}, target "
            f"{s['target']:g}, window {s['window_s']:g}s)</small></h3>"
            f"<p>error budget remaining: <b>{budget:.1%}</b> "
            f"(bad {s['bad']:g} / total {s['total']:g})</p>"
            f"<table><tr>{alerts}</tr></table>"
            f"<p>burn <code>{_sparkline(s.get('spark_burn', []))}</code>"
            f" &nbsp; budget <code>"
            f"{_sparkline(s.get('spark_budget', []))}</code></p>"
            "<table><tr><th>window</th><th>burn rate</th></tr>"
            + burns + "</table>")
    body = (
        f"<h3>{html.escape(app_id)} — SLOs</h3>"
        f"<p><a href='/'>all jobs</a> | "
        f"<a href='/jobs/{html.escape(app_id)}'>events</a></p>"
        + "".join(cards)
        + "<style>td.bad{color:#b00;font-weight:bold}"
          "td.ok{color:#080}</style>")
    return _PAGE.format(body=body)


def make_handler(index: HistoryIndex, token: str = ""):
    import threading

    # portal self-telemetry: request counts by route kind + handling
    # latency, served back on /metrics through the shared renderer.
    # Routes are a FIXED vocabulary ("other" for everything else): the
    # label set must stay bounded — a scanner walking random paths must
    # not grow the dict (or the /metrics cardinality) without limit.
    # One lock: ThreadingHTTPServer handlers mutate these concurrently.
    _KNOWN_ROUTES = ("index", "jobs", "config", "logs", "traces",
                     "requests", "tasks", "profiles", "slo", "metrics")
    http_requests: dict[str, int] = {}
    request_hist = Histogram()
    telemetry_lock = threading.Lock()
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, fmt, *args):
            log.debug("portal: " + fmt, *args)

        def _send(self, code: int, body: str, ctype="text/html"):
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype + "; charset=utf-8")
            self.send_header("Content-Length", str(len(data)))
            self.end_headers()
            self.wfile.write(data)

        def _json(self, obj):
            self._send(200 if obj is not None else 404,
                       json.dumps(obj, indent=2), "application/json")

        def _cookie_token(self) -> str:
            from http.cookies import SimpleCookie
            from urllib.parse import unquote

            jar = SimpleCookie()
            try:
                jar.load(self.headers.get("Cookie", ""))
            except Exception:
                return ""
            morsel = jar.get(_COOKIE_NAME)
            return unquote(morsel.value) if morsel else ""

        def _authorized(self, qs: dict) -> bool:
            """tony.portal.token gate on every route — the bearer-token
            analogue of the reference portal sitting behind Hadoop-secured
            infra (tony-portal/app/hadoop/Requirements.java). Accepts the
            Authorization header (API clients), the session cookie, or
            ?token= — which for browsers is immediately exchanged for an
            HttpOnly cookie + redirect in do_GET, so the token is not
            reflected into links or kept in the address bar. (It still
            transits plaintext HTTP once: bind to localhost or front with
            TLS for untrusted networks.) Cookie-less HTML scrapers should
            send `Authorization: Bearer <token>` (no redirect on that
            path) or follow the 302 with a cookie jar (curl -L -c/-b)."""
            if not token:
                return True
            header = self.headers.get("Authorization", "")
            supplied = (
                header[len("Bearer "):] if header.startswith("Bearer ")
                else qs.get("token", [""])[0] or self._cookie_token()
            )
            # compare bytes: compare_digest raises TypeError on non-ASCII str
            return hmac.compare_digest(supplied.encode(), token.encode())

        def do_GET(self):
            t0 = time.monotonic()
            try:
                return self._handle_get()
            finally:
                with telemetry_lock:
                    request_hist.observe(time.monotonic() - t0)

        def _handle_get(self):
            url = urlparse(self.path)
            qs = parse_qs(url.query)
            parts = [p for p in url.path.split("/") if p]
            route = parts[1] if parts and parts[0] == "api" and len(
                parts) > 1 else (parts[0] if parts else "index")
            if route not in _KNOWN_ROUTES:
                route = "other"
            with telemetry_lock:
                http_requests[route] = http_requests.get(route, 0) + 1
            want_json = "application/json" in self.headers.get("Accept", "") \
                or self.path.startswith("/api/")
            if parts and parts[0] == "api":
                parts = parts[1:]
            if not self._authorized(qs):
                return self._send(401, "unauthorized: supply the portal "
                                  "token (Authorization: Bearer ... or "
                                  "?token=...)", "text/plain")
            if token and "token" in qs and not want_json:
                # browser flow: swap the query token for a cookie and
                # bounce to a token-free URL so hrefs/history stay clean
                from urllib.parse import quote

                clean_qs = urlencode(
                    {k: v for k, v in qs.items() if k != "token"},
                    doseq=True,
                )
                # collapse leading '//' — browsers read a scheme-relative
                # Location as an off-site redirect (open-redirect vector)
                path = "/" + url.path.lstrip("/")
                self.send_response(302)
                self.send_header(
                    "Location", path + ("?" + clean_qs if clean_qs else "")
                )
                self.send_header(
                    "Set-Cookie",
                    f"{_COOKIE_NAME}={quote(qs['token'][0], safe='')}; "
                    "HttpOnly; Path=/; SameSite=Strict",
                )
                self.send_header("Content-Length", "0")
                self.end_headers()
                return None
            try:
                if not parts:
                    jobs = index.jobs()
                    if want_json:
                        # back-compat: the bare JSON index returns the FULL
                        # list; explicit sort/page params opt in to an
                        # envelope carrying the pagination metadata
                        if not ({"sort", "dir", "page", "per"} & qs.keys()):
                            return self._json(jobs)
                        page, info = sort_page_jobs(jobs, qs)
                        return self._json({"jobs": page, **info})
                    page, info = sort_page_jobs(jobs, qs)
                    return self._send(200, _jobs_html(page, info))
                if parts[0] == "metrics":
                    n_jobs = len(index.jobs())
                    r = PromRenderer()
                    with telemetry_lock:
                        for route_name, n in sorted(http_requests.items()):
                            r.counter("portal_http_requests_total", n,
                                      "portal GET requests by route",
                                      labels={"route": route_name})
                        r.histogram("portal_request_seconds", request_hist,
                                    "portal request handling time")
                    r.gauge("portal_jobs_indexed", n_jobs,
                            "jobs visible in the history index")
                    data = r.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type", PROM_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(data)))
                    self.end_headers()
                    self.wfile.write(data)
                    return None
                kind, app_id = parts[0], parts[1] if len(parts) > 1 else ""
                if kind == "traces":
                    traces = index.traces(app_id)
                    if want_json or traces is None:
                        return self._json(traces)
                    return self._send(
                        200, _request_timeline_html(app_id, traces))
                if kind == "requests":
                    merged = index.merged_traces(app_id)
                    if len(parts) > 2:
                        # one merged trace's cross-tier waterfall
                        trace = (merged or {}).get(parts[2])
                        if want_json or trace is None:
                            return self._json(trace)
                        return self._send(
                            200, _request_waterfall_html(app_id, trace))
                    if want_json or merged is None:
                        return self._json(merged)
                    return self._send(
                        200, _requests_list_html(app_id, merged))
                if kind == "tasks":
                    traces = index.task_traces(app_id)
                    if want_json or traces is None:
                        return self._json(traces)
                    return self._send(
                        200, _task_timeline_html(app_id, traces))
                if kind == "profiles":
                    if len(parts) > 2:
                        # a single capture file (xplane proto et al):
                        # binary download, traversal-guarded by the index
                        data = index.profile_file(
                            app_id, "/".join(parts[2:]))
                        if data is None:
                            return self._send(404, "not found",
                                              "text/plain")
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         "application/octet-stream")
                        self.send_header("Content-Length", str(len(data)))
                        self.end_headers()
                        self.wfile.write(data)
                        return None
                    profiles = index.profiles(app_id)
                    if want_json or profiles is None:
                        return self._json(profiles)
                    return self._send(
                        200, _profiles_html(app_id, profiles))
                if kind == "slo":
                    data = index.slo(app_id)
                    if want_json or data is None:
                        return self._json(data)
                    return self._send(200, _slo_html(app_id, data))
                if kind == "jobs":
                    events = index.events(app_id)
                    if want_json or events is None:
                        return self._json(events)
                    return self._send(
                        200, _job_detail_html(app_id, events))
                if kind == "config":
                    return self._json(index.config(app_id))
                if kind == "logs":
                    logs = index.logs(app_id)
                    if logs is None:
                        return self._send(404, "not found", "text/plain")
                    if want_json:
                        return self._json(logs)
                    body = "".join(
                        f"<h3>{html.escape(n)}</h3><pre>{html.escape(t)}</pre>"
                        for n, t in logs.items()
                    )
                    return self._send(200, _PAGE.format(body=body))
                return self._send(404, "not found", "text/plain")
            except Exception as e:
                log.exception("portal request failed")
                return self._send(500, f"error: {e}", "text/plain")

    return Handler


def serve_portal(conf: TonyConf, port: int = 19886, block: bool = True):
    index = HistoryIndex(conf)
    token = str(conf.get(keys.PORTAL_TOKEN, "") or "")
    mover = HistoryFileMover(
        str(conf.get(keys.HISTORY_INTERMEDIATE)),
        str(conf.get(keys.HISTORY_FINISHED)),
        interval_s=conf.get_int(keys.HISTORY_MOVER_INTERVAL_MS, 30000) / 1000,
    )
    purger = HistoryFilePurger(
        str(conf.get(keys.HISTORY_FINISHED)),
        retention_sec=conf.get_int(keys.HISTORY_RETENTION_SEC, 2592000),
    )
    mover.start()

    server = ThreadingHTTPServer(("0.0.0.0", port), make_handler(index, token))
    log.info("portal on :%d", server.server_address[1])
    if block:
        try:
            purger.purge_once()
            server.serve_forever()
        finally:
            mover.stop()
            server.server_close()
    return server
