"""Closed-loop serving autoscaler + multi-tenant resource arbiter.

TonY's defining capability is YARN's resource-negotiation layer:
heterogeneous jobs sharing one cluster under quotas, with the AM
requesting and releasing containers as conditions change (PAPER.md
L3-L4). Every input and actuator for that loop already exists in this
repo — per-replica TTFT/queue telemetry (PRs 4, 7), budget-free
roll/resize/preempt (PRs 7, 9), ~1s warm-pool adoption (PR 10), a
journaled driver that survives its own death (PR 12) — and this module
closes the loop (docs/autoscaling.md):

- **AutoscaleController** — a driver-resident loop that watches the
  serving fleet's merged telemetry (per-replica ``/metrics`` TTFT
  histogram buckets, delta'd per tick into a WINDOWED fleet p99, plus
  ``/stats`` queue depths and optionally a fleet-router ``/stats``) and
  scales the serving role between ``tony.autoscale.min`` and ``max``:
  scale-up relaunches a PARKED slot through the normal launch path
  (serving replicas spawn cold by the PR 10 drain contract; the
  warm-pool adoption fast path rides the loop's capacity-RETURN leg,
  where a reclaimed training worker adopts a standby), scale-down
  SIGTERM-drains the least-loaded replica (the serve child finishes
  in-flight work; the router fails queued work over) and parks its
  slot. Hysteresis is deliberate: ``breach-ticks`` consecutive breaching
  windows before a scale-up, a full ``cooldown-s`` between decisions,
  and scale-down additionally requires the signals CLEAR (below half
  the SLO) for a whole cooldown. Every decision is journaled
  (``{"op": "scale", ...}``) before it acts, so a recovered driver
  resumes mid-cooldown with its ledger instead of flapping.

- **ResourceArbiter** — all roles share one device/slot pool
  (``tony.quota.pool-slots``; default = the sum of configured
  instances) under per-role quotas and two priority classes.  When the
  controller wants a replica and the pool is exhausted, the arbiter
  picks a donor from the ``batch`` tier (the most-held batch role's
  highest-index non-chief RUNNING worker, never below the elastic
  floor) and the driver preempt-drains it — checkpoint at the step
  boundary, budget-free, the PR 9 contract — then DETACHES the slot
  instead of relaunching (trace mark ``donated``). When serving scales
  back down, the freed capacity lets the existing elastic
  rescale-retry loop re-attach the donated slot (trace mark
  ``reclaimed``), with the checkpoint prestaged onto the returning
  worker before it joins the gang barrier (checkpoint-aware rescale
  placement, docs/autoscaling.md).

The pieces are deliberately separable: ``scrape_ttft_buckets`` /
``bucket_quantile`` are pure parsing, ``ResourceArbiter`` is pure
accounting over the session table, and ``AutoscaleController.decide``
is a pure function of (observation, clock) — each unit-testable
without HTTP, a model, or a driver (tests/test_autoscale.py).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from dataclasses import dataclass

from .api import TaskStatus
from .conf import TonyConf, keys
from .observability import parse_prom_text

log = logging.getLogger(__name__)

# the serve-side exposition families the controller windows its SLOs
# over: TTFT for admission latency, TPOT for decode inter-token latency
# (the disaggregated decode tier's own signal — docs/autoscaling.md
# "Two-tier scaling")
TTFT_FAMILY = "serving_ttft_seconds"
TPOT_FAMILY = "serving_tpot_seconds"


def scrape_ttft_buckets(text: str, family: str = TTFT_FAMILY) -> dict:
    """Cumulative bucket counts of ``family`` ({le-string: count}) from
    one Prometheus exposition payload, via the shared parser
    (observability.parse_prom_text). Per-model partitions carry a
    ``model=`` label and would double-count the unlabeled process
    aggregate, so they are excluded from the control-law sum — use
    ``scrape_bucket_partitions`` to read them."""
    fam = parse_prom_text(text).get(family)
    return fam.buckets(exclude=("model",)) if fam else {}


def _family_partitions(fam) -> dict:
    out: dict[tuple, dict[str, float]] = {}
    for name, labels, value in fam.samples:
        if not name.endswith("_bucket") or "le" not in labels:
            continue
        key = tuple(sorted((k, v) for k, v in labels.items()
                    if k != "le"))
        if not key:
            continue
        part = out.setdefault(key, {})
        le = labels["le"]
        part[le] = part.get(le, 0.0) + value
    return out


def scrape_bucket_partitions(text: str,
                             family: str = TTFT_FAMILY) -> dict:
    """Every LABELED partition of ``family``'s buckets:
    ``{(("model", "m"), ...): {le: count}}``, keyed by the sorted
    non-``le`` label items. The partitions the old private regex parser
    silently dropped — per-model and per-role latency is visible to
    callers (hub, portal, bench) even though the fleet control law
    still windows the unlabeled aggregate."""
    fam = parse_prom_text(text).get(family)
    return _family_partitions(fam) if fam else {}


def bucket_delta(prev: dict, cur: dict) -> dict:
    """Per-le delta of two cumulative bucket snapshots. A replica
    restart resets its counters — a negative delta clamps to the
    CURRENT value (the fresh process's whole history is the window)."""
    out = {}
    for le, v in cur.items():
        d = v - prev.get(le, 0.0)
        out[le] = v if d < 0 else d
    return out


def bucket_quantile(buckets: dict, q: float) -> float | None:
    """q-th quantile from cumulative {le: count} buckets (linear within
    the winning bucket, the PromQL convention); None on no samples."""
    def le_key(le: str) -> float:
        return float("inf") if le in ("+Inf", "inf") else float(le)

    items = sorted(buckets.items(), key=lambda kv: le_key(kv[0]))
    if not items:
        return None
    total = items[-1][1]
    if total <= 0:
        return None
    rank = q * total
    lo = 0.0
    prev_count = 0.0
    for le, count in items:
        if count >= rank:
            hi = le_key(le)
            if hi == float("inf"):
                return lo       # honest lower edge for an unbounded tail
            width = count - prev_count
            if width <= 0:
                return hi
            return lo + (hi - lo) * (rank - prev_count) / width
        lo, prev_count = le_key(le), count
    return le_key(items[-1][0])


@dataclass
class FleetObservation:
    """One controller tick's merged view of the serving fleet."""
    live: int = 0                   # replicas answering /stats
    queued: int = 0                 # total queued across replicas
    active: int = 0                 # busy slots across replicas
    ttft_p99_s: float | None = None  # WINDOWED fleet p99 (None = no
    #                                  completions this window)
    window_samples: int = 0         # TTFT observations in the window
    router_queued: int | None = None  # router-side QUEUE estimate
    #                                   (outstanding posts minus active;
    #                                   overlaps the replica view — the
    #                                   control law takes the max)
    # disaggregated fleets (docs/serving.md "Disaggregated serving"):
    # True when any replica advertises role prefill/decode — breach
    # attribution then names the tier to scale (queue -> prefill,
    # TTFT/TPOT -> decode)
    tiered: bool = False
    queued_prefill: int = 0         # queued on prefill-role replicas
    tpot_p99_s: float | None = None  # WINDOWED fleet decode p99/token
    # router-TIER telemetry (docs/serving.md "Router tier HA"): the
    # front-door fleet's own saturation signal — relays in flight is
    # work each router is actively proxying, so the mean per live
    # router is per-front-door load regardless of how many doors exist
    routers_live: int = 0           # routers answering /stats
    router_relay_inflight: int = 0  # in-flight relays summed across them


class FleetWatcher:
    """Polls each replica's /stats (queue) + /metrics (TTFT buckets)
    and windows the TTFT histogram by delta'ing the cumulative buckets
    between ticks, merged across replicas — the fleet-wide p99 a
    client actually experienced THIS window, not since boot."""

    def __init__(self, timeout_s: float = 2.0, hub=None):
        self.timeout_s = timeout_s
        # optional MetricsHub (tony_tpu/metricshub.py): when set, every
        # /metrics fetch routes through hub.scrape() so ONE pipeline
        # feeds the controller's windows AND the hub's retained series.
        # The hub returns the raw exposition body, so the windowing
        # below is byte-identical with or without it.
        self.hub = hub
        self._prev: dict[str, dict] = {}    # replica name -> buckets
        self._prev_tpot: dict[str, dict] = {}
        # per-replica instantaneous load (queued + active) from the
        # newest observe() — the scale-down victim picker's input
        self.last_loads: dict[str, int] = {}
        # per-replica advertised serving role from the newest /stats —
        # the tier-targeted victim picker's input
        self.last_roles: dict[str, str] = {}
        # per-ROUTER in-flight relay count from the newest observe() —
        # the router-tier scale-down victim picker's input
        self.last_router_loads: dict[str, int] = {}
        # cumulative failed fetches per target URL — rendered as
        # driver_autoscale_scrape_failures_total so a half-blind
        # controller (replica up but /metrics refusing) is VISIBLE on
        # the driver's own exposition instead of silently retaining a
        # stale baseline
        self.scrape_failures: dict[str, int] = {}
        # newest labeled bucket partitions per replica (per-model /
        # per-role TTFT the aggregate window deliberately excludes) —
        # kept for the hub/portal; the control law never reads it
        self.last_partitions: dict[str, dict] = {}

    def _get(self, url: str) -> str | None:
        try:
            with urllib.request.urlopen(url, timeout=self.timeout_s) as r:
                return r.read().decode()
        except Exception:
            return None

    def _fetch(self, url: str) -> str | None:
        """``_get`` plus per-target failure accounting."""
        body = self._get(url)
        if body is None:
            self.scrape_failures[url] = self.scrape_failures.get(url, 0) + 1
        return body

    def _fetch_metrics(self, name: str, url: str) -> str | None:
        """/metrics fetch: through the hub when one is attached (the
        scrape is retained in its TSDB), direct otherwise."""
        if self.hub is not None:
            body = self.hub.scrape(name, url)
            if body is None:
                self.scrape_failures[url] = (
                    self.scrape_failures.get(url, 0) + 1)
            return body
        return self._fetch(url)

    def observe(self, endpoints, router_stats_url: str = "",
                router_endpoints=()) -> FleetObservation:
        """``endpoints``: [(name, host, port)] of the serving role's
        RUNNING replicas (their published serve_port). Best-effort: a
        replica that answers neither probe contributes nothing.
        ``router_endpoints``: same shape for the router ROLE's front
        doors — each is scraped for its /stats ``relay_inflight`` (the
        router-tier saturation signal) and, absent an explicit
        ``router_stats_url``, their fleet views stand in for the
        router-side queue estimate."""
        obs = FleetObservation()
        window: dict[str, float] = {}
        tpot_window: dict[str, float] = {}
        loads: dict[str, int] = {}
        roles: dict[str, str] = {}
        for name, host, port in endpoints:
            base = f"http://{host}:{port}"
            st_raw = self._fetch(base + "/stats")
            if st_raw is not None:
                try:
                    st = json.loads(st_raw)
                    obs.live += 1
                    queued = int(st.get("queued", 0) or 0)
                    active = int(st.get("active", 0) or 0)
                    obs.queued += queued
                    obs.active += active
                    loads[name] = queued + active
                    role = str(st.get("role") or "both")
                    roles[name] = role
                    if role in ("prefill", "decode"):
                        obs.tiered = True
                    if role == "prefill":
                        obs.queued_prefill += queued
                except ValueError:
                    pass
            met = self._fetch_metrics(name, base + "/metrics")
            if met is None:
                continue        # baseline RETAINED: the next successful
                #                 scrape's delta covers the gap (a loaded
                #                 replica timing out one poll mid-breach
                #                 must not blind the TTFT window)
            fams = parse_prom_text(met)
            ttft_fam = fams.get(TTFT_FAMILY)
            tpot_fam = fams.get(TPOT_FAMILY)
            # labeled partitions (per-model/per-role) the aggregate
            # window excludes — retained for hub/portal visibility
            parts = {}
            if ttft_fam is not None:
                parts.update(_family_partitions(ttft_fam))
            if parts:
                self.last_partitions[name] = parts
            cur = ttft_fam.buckets(exclude=("model",)) if ttft_fam else {}
            if cur:
                prev = self._prev.get(name)
                self._prev[name] = cur
                delta = bucket_delta(prev, cur) if prev is not None else {}
                for le, v in delta.items():
                    window[le] = window.get(le, 0.0) + v
            cur_tpot = (tpot_fam.buckets(exclude=("model",))
                        if tpot_fam else {})
            if cur_tpot:
                prev = self._prev_tpot.get(name)
                self._prev_tpot[name] = cur_tpot
                delta = (bucket_delta(prev, cur_tpot)
                         if prev is not None else {})
                for le, v in delta.items():
                    tpot_window[le] = tpot_window.get(le, 0.0) + v
        # drop baselines of replicas that LEFT THE FLEET — membership,
        # not scrape success (a reused name at a new port still deltas
        # correctly: counters restart, clamp wins)
        for name in set(self._prev) - {n for n, _, _ in endpoints}:
            self._prev.pop(name, None)
            self._prev_tpot.pop(name, None)
            self.last_partitions.pop(name, None)
        self.last_loads = loads
        self.last_roles = roles
        if window:
            items = sorted(window.values())
            obs.window_samples = int(max(items)) if items else 0
            obs.ttft_p99_s = bucket_quantile(window, 0.99)
            if obs.window_samples <= 0:
                obs.ttft_p99_s = None
        if tpot_window and max(tpot_window.values()) > 0:
            obs.tpot_p99_s = bucket_quantile(tpot_window, 0.99)
        router_loads: dict[str, int] = {}
        inflight_total = 0
        active_view = 0
        saw_fleet = False
        for name, host, port in router_endpoints:
            raw = self._fetch(f"http://{host}:{port}/stats")
            if raw is None:
                continue
            try:
                st = json.loads(raw)
                relay = int(st.get("relay_inflight", 0) or 0)
                obs.routers_live += 1
                obs.router_relay_inflight += relay
                router_loads[name] = relay
                fleet = st.get("fleet")
                if isinstance(fleet, dict):
                    saw_fleet = True
                    # inflight is per-router (each door counts only its
                    # own relays — shared-nothing), so it SUMS; active
                    # is every door's poll of the same replica /stats,
                    # so the MAX view stands for the fleet
                    inflight_total += int(fleet.get("inflight", 0) or 0)
                    active_view = max(
                        active_view, int(fleet.get("active", 0) or 0))
            except (ValueError, AttributeError, TypeError):
                pass
        self.last_router_loads = router_loads
        if saw_fleet and not router_stats_url:
            obs.router_queued = max(0, inflight_total - active_view)
        if router_stats_url:
            raw = self._fetch(router_stats_url)
            if raw is not None:
                try:
                    st = json.loads(raw)
                    # the router's QUEUE estimate is outstanding posts
                    # minus actively-decoding ones: inflight alone
                    # counts admitted work twice over the replicas' own
                    # stats, and adding the router's polled `queued`
                    # copy would double-count again
                    fleet = st.get("fleet")
                    if isinstance(fleet, dict):
                        obs.router_queued = max(
                            0, int(fleet.get("inflight", 0) or 0)
                            - int(fleet.get("active", 0) or 0))
                    else:       # pre-"fleet" routers: per-replica view
                        reps = st.get("replicas") or {}
                        obs.router_queued = sum(
                            max(0, int(r.get("inflight", 0) or 0)
                                - int(r.get("active", 0) or 0))
                            for r in reps.values() if isinstance(r, dict))
                except (ValueError, AttributeError, TypeError):
                    pass
        return obs


@dataclass
class ScaleDecision:
    direction: str              # "up" | "down"
    reason: str
    # which phase tier the decision targets on a DISAGGREGATED fleet
    # ("prefill" | "decode"; "" = untiered / whole fleet): breach
    # attribution is signal-shaped — queue depth names the admission
    # bottleneck (prefill tier), TTFT/TPOT p99 names decode
    tier: str = ""


class AutoscaleController:
    """The control law, separated from its actuators. ``decide()`` is a
    pure function of (observation, now) over the controller's hysteresis
    state; the driver-resident ``tick()`` wires it to real telemetry and
    the driver's scale_up/scale_down actuators; ``start()`` runs ticks
    on a daemon thread at ``interval-s``."""

    def __init__(self, *, ttft_slo_s: float = 0.0, queue_slo: int = 0,
                 min_replicas: int = 1, max_replicas: int = 1,
                 cooldown_s: float = 30.0, breach_ticks: int = 2,
                 interval_s: float = 2.0, last_scale_t: float | None = None,
                 tpot_slo_s: float = 0.0, router_slo: float = 0.0,
                 router_min: int = 1, router_max: int = 0,
                 now_fn=time.time):
        self.ttft_slo_s = float(ttft_slo_s)
        self.tpot_slo_s = float(tpot_slo_s)
        self.queue_slo = int(queue_slo)
        # router-TIER law (docs/autoscaling.md "Three-tier signals"):
        # router_slo is the mean in-flight relays per live front door
        # above which the router tier itself is the bottleneck. 0 =
        # the router tier is not autoscaled (today's behavior).
        self.router_slo = float(router_slo)
        self.router_min = max(0, int(router_min))
        self.router_max = max(self.router_min, int(router_max))
        self.min_replicas = max(0, int(min_replicas))
        self.max_replicas = max(self.min_replicas, int(max_replicas))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self.breach_ticks = max(1, int(breach_ticks))
        self.interval_s = max(0.1, float(interval_s))
        self._now = now_fn
        # hysteresis state. last_scale_t is WALL clock (it must survive
        # a driver recovery via the journal's scale ledger); clear_since
        # is in the same clock for symmetry.
        self.last_scale_t = last_scale_t
        self._breach_streak = 0
        self._clear_since: float | None = None
        # router-tier hysteresis is SEPARATE (a router breach must not
        # arm a serving scale-up and vice versa) but the cooldown is
        # SHARED — one slot pool, and two tiers actuating in the same
        # window would race each other for it
        self._router_breach_streak = 0
        self._router_clear_since: float | None = None
        # breach windows observed inside a cooldown WE armed are
        # discounted — they still reflect the pre-actuation fleet (the
        # new replica hadn't absorbed load when those requests ran).  A
        # RECOVERED cooldown (last_scale_t via ctor) only suppresses
        # actuation: post-recovery windows are fresh evidence and may
        # pre-arm the streak.
        self._discard_until = 0.0
        # the newest observation, for /metrics
        self.last_obs = FleetObservation()
        self.decisions_up = 0
        self.decisions_down = 0

    @classmethod
    def from_conf(cls, conf: TonyConf,
                  last_scale_t: float | None = None) -> "AutoscaleController":
        return cls(
            ttft_slo_s=float(conf.get(keys.AUTOSCALE_TTFT_P99_SLO_S, 0)
                             or 0),
            queue_slo=conf.get_int(keys.AUTOSCALE_QUEUE_DEPTH_SLO, 0),
            min_replicas=conf.get_int(keys.AUTOSCALE_MIN, 1),
            max_replicas=conf.get_int(keys.AUTOSCALE_MAX, 0),
            cooldown_s=float(conf.get(keys.AUTOSCALE_COOLDOWN_S, 30) or 0),
            breach_ticks=conf.get_int(keys.AUTOSCALE_BREACH_TICKS, 2),
            interval_s=float(conf.get(keys.AUTOSCALE_INTERVAL_S, 2) or 2),
            tpot_slo_s=float(conf.get(keys.AUTOSCALE_TPOT_P99_SLO_S, 0)
                             or 0),
            router_slo=float(conf.get(keys.AUTOSCALE_ROUTER_RELAY_SLO, 0)
                             or 0),
            router_min=conf.get_int(keys.AUTOSCALE_ROUTER_MIN, 1),
            last_scale_t=last_scale_t)

    # ------------------------------------------------------------ control law
    def _breaching(self, obs: FleetObservation) -> tuple[str, str] | None:
        """Which SLO (if any) this observation breaches, as (reason,
        tier). The router's inflight/queued view OVERLAPS the replicas'
        own /stats (a router-posted request admitted server-side
        appears in both), so the queue signal is the MAX of the two
        views, never the sum — summing would breach at half the
        configured SLO. On a TIERED (disaggregated) fleet the breach
        names the tier whose phase the signal measures: queue depth is
        admission pressure (prefill), TTFT/TPOT p99 is decode latency
        (decode). Untiered fleets get tier "" — today's behavior."""
        queued = max(obs.queued, obs.router_queued or 0)
        if self.queue_slo > 0 and queued > self.queue_slo:
            return (f"queue depth {queued} > SLO {self.queue_slo}",
                    "prefill" if obs.tiered else "")
        if (self.ttft_slo_s > 0 and obs.ttft_p99_s is not None
                and obs.ttft_p99_s > self.ttft_slo_s):
            return (f"windowed ttft p99 {obs.ttft_p99_s:.3f}s > SLO "
                    f"{self.ttft_slo_s}s",
                    "decode" if obs.tiered else "")
        if (self.tpot_slo_s > 0 and obs.tpot_p99_s is not None
                and obs.tpot_p99_s > self.tpot_slo_s):
            return (f"windowed tpot p99 {obs.tpot_p99_s:.4f}s > SLO "
                    f"{self.tpot_slo_s}s",
                    "decode" if obs.tiered else "")
        return None

    def _clear(self, obs: FleetObservation) -> bool:
        """All signals comfortably under HALF their SLO (a no-traffic
        window — no completions, empty queue — counts as clear)."""
        queued = max(obs.queued, obs.router_queued or 0)
        if self.queue_slo > 0 and queued > self.queue_slo / 2:
            return False
        if (self.ttft_slo_s > 0 and obs.ttft_p99_s is not None
                and obs.ttft_p99_s > self.ttft_slo_s / 2):
            return False
        if (self.tpot_slo_s > 0 and obs.tpot_p99_s is not None
                and obs.tpot_p99_s > self.tpot_slo_s / 2):
            return False
        return True

    def decide(self, obs: FleetObservation, n_running: int,
               now: float | None = None,
               n_routers: int | None = None) -> ScaleDecision | None:
        """One control-law evaluation. ``n_running`` is the serving
        role's current non-parked replica count (launched or launching);
        ``n_routers`` the router role's (None = no router tier — the
        router law never evaluates, byte-identical to the two-tier
        controller). Returns a decision or None; the CALLER journals +
        actuates, and reports success back via ``note_scaled`` (an
        actuation that could not proceed — e.g. awaiting a donation
        drain — must not start the cooldown, or the pending scale-up
        would starve). The serving law is evaluated FIRST: when both
        tiers breach, capacity goes where the tokens are made."""
        now = self._now() if now is None else now
        self.last_obs = obs
        decision = self._decide_serving(obs, n_running, now)
        if decision is None:
            decision = self._decide_router(obs, n_routers, now)
        return decision

    def _decide_serving(self, obs: FleetObservation, n_running: int,
                        now: float) -> ScaleDecision | None:
        breach = self._breaching(obs)
        in_cooldown = (self.last_scale_t is not None
                       and now - self.last_scale_t < self.cooldown_s)
        if n_running < self.min_replicas and not in_cooldown:
            # floor enforcement: a replica parked by budget exhaustion
            # (or a recovered formation below min) relaunches without
            # waiting for an SLO breach
            return ScaleDecision(
                "up", f"{n_running} running < min {self.min_replicas}")
        if breach is not None:
            reason, tier = breach
            self._clear_since = None
            if now < self._discard_until:
                return None
            self._breach_streak += 1
            if (self._breach_streak >= self.breach_ticks
                    and not in_cooldown and n_running < self.max_replicas):
                return ScaleDecision("up", reason, tier=tier)
            return None
        self._breach_streak = 0
        if not self._clear(obs):
            self._clear_since = None
            return None
        if self._clear_since is None:
            self._clear_since = now
        if (not in_cooldown and n_running > self.min_replicas
                and now - self._clear_since >= self.cooldown_s):
            return ScaleDecision(
                "down", f"signals clear for {now - self._clear_since:.0f}s")
        return None

    def _decide_router(self, obs: FleetObservation,
                       n_routers: int | None,
                       now: float) -> ScaleDecision | None:
        """The router-TIER law (docs/autoscaling.md "Three-tier
        signals"): front doors scale on their OWN saturation signal —
        mean in-flight relays per live router — never on the serving
        tier's latency SLOs (a slow model must add replicas, not
        routers). Same hysteresis shape as serving: breach-ticks
        streak up, clear-below-half-SLO-for-a-full-cooldown down,
        floor rule for a fleet below min, shared cooldown."""
        if self.router_slo <= 0 or n_routers is None:
            return None
        in_cooldown = (self.last_scale_t is not None
                       and now - self.last_scale_t < self.cooldown_s)
        if n_routers < self.router_min and not in_cooldown:
            return ScaleDecision(
                "up", f"{n_routers} routers < min {self.router_min}",
                tier="router")
        if not obs.routers_live:
            return None     # no router answered /stats: never actuate
            #                 the tier blind (the floor rule above still
            #                 relaunches a fleet the DRIVER knows is
            #                 short)
        mean = obs.router_relay_inflight / obs.routers_live
        if mean > self.router_slo:
            self._router_clear_since = None
            if now < self._discard_until:
                return None
            self._router_breach_streak += 1
            if (self._router_breach_streak >= self.breach_ticks
                    and not in_cooldown and n_routers < self.router_max):
                return ScaleDecision(
                    "up",
                    f"router relay inflight {mean:.1f}/door > SLO "
                    f"{self.router_slo:g}", tier="router")
            return None
        self._router_breach_streak = 0
        if mean > self.router_slo / 2:
            self._router_clear_since = None
            return None
        if self._router_clear_since is None:
            self._router_clear_since = now
        if (not in_cooldown and n_routers > self.router_min
                and now - self._router_clear_since >= self.cooldown_s):
            return ScaleDecision(
                "down",
                f"router signal clear for "
                f"{now - self._router_clear_since:.0f}s", tier="router")
        return None

    def cooldown_remaining(self, now: float | None = None) -> float:
        """Seconds left in the armed cooldown (0.0 when none is armed).
        The serving layer folds this into 429 ``Retry-After`` hints: a
        client told to come back AFTER the cooldown lands when capacity
        can actually have changed, instead of re-slamming a fleet that
        is contractually frozen."""
        if self.last_scale_t is None:
            return 0.0
        now = self._now() if now is None else now
        return max(0.0, self.cooldown_s - (now - self.last_scale_t))

    def note_scaled(self, direction: str, now: float | None = None) -> None:
        """The actuation actually happened: arm the cooldown."""
        now = self._now() if now is None else now
        self.last_scale_t = now
        self._breach_streak = 0
        self._clear_since = None
        self._router_breach_streak = 0
        self._router_clear_since = None
        self._discard_until = now + self.cooldown_s
        if direction == "up":
            self.decisions_up += 1
        else:
            self.decisions_down += 1


class ResourceArbiter:
    """Quota + priority accounting over one shared slot pool. Pure
    bookkeeping over the session's task table — the driver actuates
    (preempt-drain, detach, relaunch); the arbiter only answers
    ``free()`` / ``can_grant()`` / ``pick_donor()``."""

    def __init__(self, session, specs=None, pool_slots: int = 0):
        self.session = session
        specs = list(specs if specs is not None
                     else session.role_specs.values())
        self.specs = {s.name: s for s in specs}
        self.pool_slots = (int(pool_slots) if pool_slots
                           else sum(s.instances for s in specs))
        self.donations = 0          # batch slots preempt-drained for
        #                             interactive demand
        self.reclaims = 0           # donated slots returned to batch

    def held(self, role: str) -> int:
        """Slots a role currently occupies: launched (or launching),
        non-terminal, non-detached tasks. Parked/donated slots are
        detached, so they count as free pool capacity."""
        n = 0
        for t in self.session.tasks.get(role, []):
            if t.task_id in self.session.detached:
                continue
            if t.status in (TaskStatus.NEW,) or t.status.is_terminal():
                continue
            n += 1
        return n

    def held_total(self) -> int:
        return sum(self.held(r) for r in self.specs)

    def free(self) -> int:
        return self.pool_slots - self.held_total()

    def quota(self, role: str) -> int:
        spec = self.specs.get(role)
        if spec is None:
            return 0
        return spec.instances if spec.quota < 0 else spec.quota

    def can_grant(self, role: str) -> bool:
        """May ``role`` take one more slot right now (quota + free
        pool)?"""
        return (self.held(role) < self.quota(role)) and self.free() >= 1

    def over_quota(self, role: str) -> bool:
        return self.held(role) >= self.quota(role)

    def batch_floor(self, role: str, elastic_min: int = 1) -> int:
        """How low donation may drain a batch role: the elastic floor
        (survivors must still form a gang)."""
        return max(1, int(elastic_min))

    def pick_donor(self, for_role: str, elastic_min: int = 1,
                   busy: set | None = None) -> str | None:
        """The batch task that yields its slot to ``for_role``: from the
        MOST-held batch role (most capacity to spare), its highest-index
        RUNNING non-chief task — deterministic, chief-safe, floor-safe.
        ``busy`` excludes tasks already mid-drain for another ledger."""
        busy = busy or set()
        candidates = []
        for name, spec in self.specs.items():
            if name == for_role or spec.priority_class != "batch":
                continue
            running = [
                t for t in self.session.tasks.get(name, [])
                if t.task_id not in self.session.detached
                and t.status == TaskStatus.RUNNING
                and t.task_id not in busy
                and not self.session.is_chief(t.name, t.index)
                # index 0 is the role's gang anchor (completion policy,
                # rank-0 rendezvous) even when no explicit chief role
                # exists — never donated
                and t.index != 0]
            if self.held(name) - 1 < self.batch_floor(name, elastic_min):
                continue
            if running:
                candidates.append((self.held(name), name, running))
        if not candidates:
            return None
        _, _, running = max(candidates, key=lambda c: (c[0], c[1]))
        return max(running, key=lambda t: t.index).task_id

    def snapshot(self) -> dict:
        """The /metrics + journal-debug view."""
        return {
            "pool_slots": self.pool_slots,
            "free": self.free(),
            "held": {r: self.held(r) for r in sorted(self.specs)},
            "quota": {r: self.quota(r) for r in sorted(self.specs)},
            "class": {r: self.specs[r].priority_class
                      for r in sorted(self.specs)},
            "donations": self.donations,
            "reclaims": self.reclaims,
        }


class AutoscaleRunner(threading.Thread):
    """The driver-resident loop: every ``interval-s``, observe the
    fleet, evaluate the control law, and actuate through the driver.
    All actuation goes through ``driver.autoscale_tick()`` so the
    scale/donate/park ledger discipline lives next to the other ledgers
    in driver.py."""

    def __init__(self, driver, controller: AutoscaleController,
                 watcher: FleetWatcher | None = None,
                 router_stats_url: str = ""):
        super().__init__(name="autoscaler", daemon=True)
        self.driver = driver
        self.controller = controller
        self.watcher = watcher or FleetWatcher()
        self.router_stats_url = router_stats_url
        self.stop_event = threading.Event()

    def run(self) -> None:
        while not self.stop_event.wait(self.controller.interval_s):
            try:
                self.driver.autoscale_tick(self.controller, self.watcher,
                                           self.router_stats_url)
            except Exception:
                # one bad tick (replica mid-restart, transient HTTP)
                # must not end the loop for the life of the job
                log.exception("autoscale tick failed")

    def shutdown(self) -> None:
        self.stop_event.set()
