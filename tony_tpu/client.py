"""Submission client.

Mirrors the reference TonyClient (tony-core/.../TonyClient.java): resolves the
layered config (:666-700), validates caps (:796-866), stages the job dir +
frozen final config (:232-315), launches the driver (submitApplication:317-353
— locally a subprocess; on a TPU fleet the driver host), then polls
application state + task infos, firing listeners (monitorApplication:
1039-1107, updateTaskInfoAndReturn:1196-1214), and finally signals the driver
to exit (signalAMToFinish:1109-1119). The programmatic callback API mirrors
client/CallbackHandler.java + client/TaskUpdateListener.java (used the same
way by notebook submitters and tests).
"""

from __future__ import annotations

import json
import logging
import os
import secrets
import shutil
import subprocess
import sys
import time
import uuid
from pathlib import Path
from typing import Callable, Protocol

from . import constants as c
from .api import JobStatus, TaskInfo
from .conf import TonyConf, keys
from .rpc import RpcClient

log = logging.getLogger(__name__)


class CallbackHandler(Protocol):
    def on_application_id_received(self, app_id: str) -> None: ...


TaskUpdateListener = Callable[[list[TaskInfo]], None]


def new_app_id() -> str:
    return f"tony_{int(time.time())}_{uuid.uuid4().hex[:8]}"


class TonyClient:
    def __init__(
        self,
        conf: TonyConf,
        callback_handler: CallbackHandler | None = None,
        poll_interval_s: float = 0.2,
    ):
        self.conf = conf
        self.callback_handler = callback_handler
        self.poll_interval_s = poll_interval_s
        self._listeners: list[TaskUpdateListener] = []
        self.app_id: str = ""
        self.job_dir: Path | None = None
        self.token: str = ""
        self.final_state: dict = {}
        self.task_infos: list[TaskInfo] = []
        self._driver_proc: subprocess.Popen | None = None
        self._rpc: RpcClient | None = None

    def add_listener(self, listener: TaskUpdateListener) -> None:
        self._listeners.append(listener)

    # ------------------------------------------------------------ submission
    def submit(self) -> str:
        """Stage and launch the driver; returns the app id."""
        self.conf.validate()
        self.app_id = new_app_id()
        if self.callback_handler is not None:
            self.callback_handler.on_application_id_received(self.app_id)

        staging = Path(str(self.conf.get(keys.STAGING_DIR)))
        self.job_dir = staging / self.app_id
        self.job_dir.mkdir(parents=True, exist_ok=True)
        self._stage_resources()
        self.token = (
            secrets.token_hex(16)
            if self.conf.get_bool(keys.SECURITY_TOKEN_ENABLED, True) else ""
        )
        # stamp framework build identity into the frozen config (reference
        # VersionInfo injection, TonyClient.java:195)
        from .utils import version

        version.inject(self.conf)
        self.conf.write_final(self.job_dir)
        self._ship_archive()

        env = {**os.environ, c.ENV_TOKEN: self.token}
        # make this package importable in the driver/executor processes no
        # matter their cwd (the local analogue of shipping the fat jar,
        # ClusterSubmitter.java:49-84)
        pkg_parent = str(Path(__file__).resolve().parent.parent)
        existing = env.get("PYTHONPATH", "")
        if pkg_parent not in existing.split(os.pathsep):
            env["PYTHONPATH"] = (
                pkg_parent + (os.pathsep + existing if existing else "")
            )
        driver_log = open(self.job_dir / "driver.log", "ab")
        self._driver_proc = subprocess.Popen(
            [
                # -S: skip site hooks (sitecustomize imports jax; the driver
                # must never hold a TPU anyway — reference warns the same for
                # AM-with-GPU, TonyClient.java:528-531)
                sys.executable, "-S", "-m", "tony_tpu.driver",
                "--job-dir", str(self.job_dir), "--app-id", self.app_id,
            ],
            env=env,
            stdout=driver_log,
            stderr=subprocess.STDOUT,
            start_new_session=True,
        )
        log.info("submitted %s (driver pid %d)", self.app_id, self._driver_proc.pid)
        return self.app_id

    def _stage_resources(self) -> None:
        """Copy src dir / per-role resources into the job dir — the local
        analogue of the HDFS .tony/<appId> staging upload
        (TonyClient.processFinalTonyConf:232-315)."""
        src = str(self.conf.get(keys.SRC_DIR, "") or "")
        if src and Path(src).is_dir():
            dest = self.job_dir / "src"
            if not dest.exists():
                shutil.copytree(src, dest)
            self.conf.set(keys.SRC_DIR, str(dest))
        # per-role resources: path[#alias][::archive] (reference
        # LocalizableResource.java)
        from .utils import localization as loc

        for spec in self.conf.role_specs():
            if not spec.resources:
                continue
            staged = loc.stage_resources(
                loc.parse_resources(spec.resources), self.job_dir
            )
            self.conf.set(
                keys.role_key(spec.name, "resources"), loc.serialize(staged)
            )

    def _ship_archive(self) -> None:
        """Build (and optionally upload) the job archive so executors on
        hosts without the staging FS can fetch the job — the reference's
        HDFS staging upload (TonyClient.java:232-315). Runs when an
        archive URI is configured, localization is forced, or the
        provisioner launches on remote hosts."""
        from .utils import shipping

        template_uri = str(self.conf.get(keys.APPLICATION_ARCHIVE_URI, "") or "")
        # {app} placeholder -> per-application path, so one static config
        # serves many submissions without archives clobbering each other
        uri = template_uri.replace("{app}", self.app_id)
        localize = self.conf.get_bool(keys.TASK_LOCALIZE, False)
        prov = str(self.conf.get(keys.CLUSTER_PROVISIONER, "local")).lower()
        if not uri and not localize and prov == "local":
            return
        archive = shipping.build_job_archive(self.job_dir)
        digest = shipping.sha256_file(archive)
        if not uri:
            # shared/local FS default; real fleets set an uploadable URI
            # (gs://... + upload-cmd) or scp://<client-host>:<archive>
            uri = str(archive)
        upload_cmd = str(
            self.conf.get(keys.APPLICATION_ARCHIVE_UPLOAD_CMD, "") or ""
        )
        if (prov != "local" and not upload_cmd
                and not uri.startswith(("scp://", "gs://", "http://",
                                        "https://"))):
            # a client-local filesystem path frozen as the URI is only
            # fetchable from remote hosts over a shared FS; without one the
            # executors die in localization with a raw FileNotFoundError,
            # so name the misconfiguration here where it is actionable
            log.warning(
                "provisioner %r launches on remote hosts but the job-archive "
                "URI %r is a local filesystem path and no %s is set — "
                "executors will fail localization unless %s is on a shared "
                "filesystem", prov, uri,
                keys.APPLICATION_ARCHIVE_UPLOAD_CMD, uri,
            )
        # freeze the RESOLVED uri + digest for the driver, but restore the
        # template in the in-memory conf — a caller reusing one conf object
        # for several submissions must not inherit this job's resolved path
        # or hash (executors read the archive copy of the conf, where both
        # are irrelevant: the digest cannot live inside the bytes it hashes,
        # so it reaches executors via launch env, not the archive)
        prior_sha = str(self.conf.get(keys.APPLICATION_ARCHIVE_SHA256, "") or "")
        self.conf.set(keys.APPLICATION_ARCHIVE_URI, uri)
        self.conf.set(keys.APPLICATION_ARCHIVE_SHA256, digest)
        try:
            self.conf.write_final(self.job_dir)
        finally:
            self.conf.set(keys.APPLICATION_ARCHIVE_URI, template_uri)
            self.conf.set(keys.APPLICATION_ARCHIVE_SHA256, prior_sha)
        if upload_cmd:
            shipping.upload_archive(archive, uri, upload_cmd)

    # ------------------------------------------------------------ monitoring
    def _connect(self, timeout_s: float = 60.0) -> RpcClient:
        """Poll for the driver's advertised endpoint (plays the reference's
        poll-app-report-for-AM-host-port role, TonyClient.java:1216-1237)."""
        deadline = time.time() + timeout_s
        info_path = self.job_dir / c.DRIVER_INFO_FILE
        while time.time() < deadline:
            if self._driver_proc is not None and self._driver_proc.poll() is not None:
                raise RuntimeError(
                    f"driver exited early with code {self._driver_proc.returncode}; "
                    f"see {self.job_dir / 'driver.log'}"
                )
            if info_path.exists():
                info = json.loads(info_path.read_text())
                from .rpc.protocol import derive_role_key
                # the client signs with its derived client-role key —
                # executors (who hold only the executor key) cannot forge
                # these calls (driver-side ACL on finish_application)
                return RpcClient(
                    info["host"], info["port"],
                    token=derive_role_key(self.token, "client"),
                    role="client" if self.token else "",
                )
            time.sleep(0.05)
        raise TimeoutError("driver did not advertise its endpoint in time")

    def monitor(self) -> JobStatus:
        """Poll until terminal; fire listeners on task-info changes; ack with
        finish_application so the driver can exit."""
        self._rpc = self._connect()
        last_infos_json = ""
        status = JobStatus.RUNNING
        while True:
            try:
                state = self._rpc.call("get_application_state")
                infos = self._rpc.call("get_task_infos")
            except (ConnectionError, OSError):
                if self._driver_proc is not None and self._driver_proc.poll() is not None:
                    # driver died; a non-terminal last-seen state means the
                    # job did not finish — report failure (reference: client
                    # keeps polling RM across AM attempts; with no external
                    # RM, a dead driver IS the terminal signal)
                    log.error("driver process exited (code %s)",
                              self._driver_proc.returncode)
                    last = self.final_state.get("status", "")
                    status = (
                        JobStatus(last)
                        if last in JobStatus.__members__ and JobStatus(last).is_terminal()
                        else JobStatus.FAILED
                    )
                    self.final_state.setdefault(
                        "message", f"driver exited (code {self._driver_proc.returncode})"
                    )
                    return status
                time.sleep(self.poll_interval_s)
                continue
            self.final_state = state
            infos_json = json.dumps(infos, sort_keys=True)
            if infos_json != last_infos_json:
                last_infos_json = infos_json
                self.task_infos = [TaskInfo.from_dict(d) for d in infos]
                for listener in self._listeners:
                    try:
                        listener(self.task_infos)
                    except Exception:
                        log.exception("task update listener failed")
            status = JobStatus(state["status"])
            if status.is_terminal():
                break
            time.sleep(self.poll_interval_s)
        try:
            self._rpc.call("finish_application")
        except Exception:
            pass
        if self._driver_proc is not None:
            try:
                self._driver_proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                log.warning("driver slow to exit; killing")
                self._driver_proc.kill()
        if status != JobStatus.SUCCEEDED:
            log.error("job %s finished %s: %s", self.app_id, status.value,
                      self.final_state.get("message", ""))
        return status

    def run(self) -> int:
        """submit + monitor; returns a shell exit code."""
        self.submit()
        status = self.monitor()
        return 0 if status == JobStatus.SUCCEEDED else 1

    def stop(self) -> None:
        """Force-kill the application (reference forceKillApplication via the
        shutdown hook in ClusterSubmitter.java:49-84)."""
        if self._driver_proc is not None and self._driver_proc.poll() is None:
            import signal as _signal

            try:
                os.killpg(self._driver_proc.pid, _signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
