"""Port reservation: ephemeral vs reusable (SO_REUSEPORT) server ports.

Mirrors the reference's ServerPort abstraction (tony-core/.../EphemeralPort.java,
ReusablePort.java:39-52,204-237 and resources/reserve_reusable_port.py): an
executor must advertise a port to the driver *before* the user process exists,
yet the user's framework must later bind that same port. Two strategies:

- EphemeralPort: bind(0), hold the socket, release just before exec. There is
  a race window between release and the child's bind (reference notes the TF
  >= 2.3 gRPC failure mode this causes).
- ReusablePort: bind with SO_REUSEPORT and keep holding the socket across the
  exec; a child that also sets SO_REUSEPORT (gRPC servers do by default, and
  jax.distributed's coordinator can) binds the same port with no race window.
  The reference forks a python sidecar to hold the socket because Java can't
  set SO_REUSEPORT portably; here the executor process holds it directly.

Opt-in mirrors the reference's TF_GRPC_REUSE_PORT / TB_SERVER_REUSE_PORT envs
(TaskExecutor.java:119-152) via tony.task.port-reuse-enabled /
tony.task.tb-port-reuse-enabled.
"""

from __future__ import annotations

import socket


def reuse_port_supported() -> bool:
    """SO_REUSEPORT exists on Linux >= 3.9 and macOS; absent on Windows."""
    return hasattr(socket, "SO_REUSEPORT")


class ServerPort:
    """A held TCP port reservation. `port` is valid until `release()`."""

    def __init__(self, sock: socket.socket):
        self._sock: socket.socket | None = sock
        self.port: int = sock.getsockname()[1]

    @property
    def held(self) -> bool:
        return self._sock is not None

    def release(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def release_before_exec(self) -> None:
        """Called just before the user process is spawned. Ephemeral
        reservations must free the port here (accepting the race window);
        held strategies override this as a no-op."""
        self.release()

    def __enter__(self) -> "ServerPort":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class EphemeralPort(ServerPort):
    """Plain bind(0) reservation — must be released before the child binds
    (reference EphemeralPort.java; release-before-exec dance
    TaskExecutor.java:201-233)."""

    @classmethod
    def create(cls) -> "EphemeralPort":
        sock = socket.socket()
        sock.bind(("", 0))
        return cls(sock)


class ReusablePort(ServerPort):
    """SO_REUSEPORT reservation held across the child's exec — no race window
    (reference ReusablePort.create, ReusablePort.java:204-237)."""

    @classmethod
    def create(cls, port: int = 0) -> "ReusablePort":
        if not reuse_port_supported():
            raise OSError("SO_REUSEPORT is not supported on this platform")
        sock = socket.socket()
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT, 1)
        # bound but NOT listening: reserves the port (plain binds collide)
        # without joining the kernel's reuseport listener group — a listening
        # reservation would be load-balanced a share of the child's incoming
        # connections and never accept them
        sock.bind(("", port))
        return cls(sock)

    def release_before_exec(self) -> None:
        """Held across the exec — the child rebinds while we still hold."""


def allocate(reuse: bool) -> ServerPort:
    """Pick the strategy the way the executor's setupPorts does
    (TaskExecutor.java:88-100,119-152): reusable iff requested AND supported."""
    if reuse:
        if reuse_port_supported():
            return ReusablePort.create()
        import logging

        logging.getLogger(__name__).warning(
            "SO_REUSEPORT requested but unsupported on this platform; "
            "falling back to an ephemeral port (release-before-exec race window)"
        )
    return EphemeralPort.create()
