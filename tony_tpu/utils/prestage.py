"""Checkpoint prestage — a pure-filesystem helper the EXECUTOR runs.

Lives under utils (not tony_tpu/train/) on purpose: executors run
``python -S`` without the training stack, and ``tony_tpu.train``'s
package __init__ imports jax at module level — importing the helper
from there crashed the capacity-return relaunch before it could
register (found by ``bench.py --autoscale``). ``train.checkpoint``
re-exports the name for training-side callers.
"""

from __future__ import annotations

import logging
from pathlib import Path

log = logging.getLogger(__name__)


def prestage_checkpoint(directory: str) -> dict | None:
    """Checkpoint-aware rescale placement (docs/autoscaling.md): read
    every file of the NEWEST complete checkpoint under ``directory``
    so the bytes are local (page cache on a local FS; the actual fetch
    on a remote mount) BEFORE the worker joins the gang barrier — the
    restore the training child runs after the barrier then hits warm
    data instead of serializing cold I/O behind the whole gang.

    Pure filesystem walk (no orbax import — the executor calls this
    before the child exists): step directories are the orbax layout's
    integer-named children; in-progress/tmp saves are skipped. Returns
    ``{"step", "files", "bytes"}`` or None when there is nothing
    staged yet (first launch) — never raises (a prestage failure must
    degrade to the old cold-restore behavior, not fail the relaunch)."""
    try:
        root = Path(directory)
        if not root.is_dir():
            return None
        steps = []
        for child in root.iterdir():
            # isdigit alone is the whole guard: orbax finalizes via
            # tmp+rename and its in-progress dirs are suffixed
            # ("<step>.orbax-checkpoint-tmp-<n>"), never bare integers
            if child.is_dir() and child.name.isdigit():
                steps.append(int(child.name))
        if not steps:
            return None
        step = max(steps)
        n_files = 0
        n_bytes = 0
        for p in sorted((root / str(step)).rglob("*")):
            if not p.is_file():
                continue
            n_files += 1
            with open(p, "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    n_bytes += len(chunk)
        return {"step": step, "files": n_files, "bytes": n_bytes}
    except OSError:
        log.exception("checkpoint prestage of %s failed; the child "
                      "restores cold", directory)
        return None
