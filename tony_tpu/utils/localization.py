"""Per-role resource localization.

Mirrors the reference's LocalizableResource (tony-core/.../LocalizableResource.java):
resource strings ``path[#alias][::archive]`` are staged into the job dir by the
client and materialized into each task's working directory by the executor —
``::archive`` entries are unzipped (the reference's venv/src-zip handling,
Utils.extractResources, util/Utils.java:758-771).
"""

from __future__ import annotations

import shutil
import zipfile
from dataclasses import dataclass
from pathlib import Path

ARCHIVE_SUFFIX = "::archive"


@dataclass(frozen=True)
class ResourceSpec:
    path: str
    alias: str
    archive: bool

    @classmethod
    def parse(cls, raw: str) -> "ResourceSpec":
        raw = raw.strip()
        archive = raw.endswith(ARCHIVE_SUFFIX)
        if archive:
            raw = raw[: -len(ARCHIVE_SUFFIX)]
        path, _, alias = raw.partition("#")
        if not path:
            raise ValueError(f"empty resource path in {raw!r}")
        return cls(path=path, alias=alias or Path(path).name, archive=archive)


def parse_resources(raws: list[str]) -> list[ResourceSpec]:
    return [ResourceSpec.parse(r) for r in raws if r.strip()]


def stage_resources(specs: list[ResourceSpec], staging_dir: str | Path) -> list[ResourceSpec]:
    """Client side: copy resources into <staging>/resources, return specs
    rewritten to the staged locations."""
    dest_root = Path(staging_dir) / "resources"
    dest_root.mkdir(parents=True, exist_ok=True)
    staged = []
    for spec in specs:
        src = Path(spec.path)
        if not src.exists():
            raise FileNotFoundError(f"resource not found: {spec.path}")
        dest = dest_root / src.name
        if src.is_dir():
            if not dest.exists():
                shutil.copytree(src, dest)
        else:
            shutil.copy2(src, dest)
        staged.append(ResourceSpec(path=str(dest), alias=spec.alias, archive=spec.archive))
    return staged


def localize_resources(specs: list[ResourceSpec], work_dir: str | Path) -> list[Path]:
    """Executor side: materialize staged resources under work_dir by alias,
    expanding ``::archive`` zips."""
    work = Path(work_dir)
    work.mkdir(parents=True, exist_ok=True)
    out = []
    for spec in specs:
        src = Path(spec.path)
        target = work / spec.alias
        if spec.archive:
            target.mkdir(parents=True, exist_ok=True)
            with zipfile.ZipFile(src) as zf:
                zf.extractall(target)
        elif src.is_dir():
            if not target.exists():
                shutil.copytree(src, target)
        else:
            if not target.exists():
                shutil.copy2(src, target)
        out.append(target)
    return out


def serialize(specs: list[ResourceSpec]) -> str:
    return ",".join(
        s.path + (f"#{s.alias}" if s.alias != Path(s.path).name else "")
        + (ARCHIVE_SUFFIX if s.archive else "")
        for s in specs
    )
