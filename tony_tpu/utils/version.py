"""Build/version identification injected into the resolved job config.

Mirrors the reference VersionInfo (tony-core/.../util/VersionInfo.java, used
at TonyClient.java:195): version + VCS revision/branch + build user are
stamped into the frozen config so the portal and history files record exactly
which framework build ran the job. The reference bakes these in at compile
time from a generated properties file; here they are resolved lazily from the
installed package metadata and (when running from a checkout) `git`.
"""

from __future__ import annotations

import functools
import os
import subprocess

VERSION = "0.1.0"

# conf keys the client stamps (reference injectVersionInfo -> tony.version.*)
VERSION_KEY = "tony.version"
REVISION_KEY = "tony.version.revision"
BRANCH_KEY = "tony.version.branch"
BUILD_USER_KEY = "tony.version.user"


def _git(*args: str) -> str:
    # only trust git when the framework itself is the checkout — an installed
    # package may sit inside some unrelated repository (a user project's
    # venv), whose SHA must not be stamped as the framework build identity
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    repo_root = os.path.dirname(pkg_root)
    if not os.path.isdir(os.path.join(repo_root, ".git")):
        return ""
    try:
        out = subprocess.run(
            ["git", *args], cwd=pkg_root,
            capture_output=True, text=True, timeout=5,
        )
        return out.stdout.strip() if out.returncode == 0 else ""
    except (OSError, subprocess.TimeoutExpired):
        return ""


@functools.lru_cache(maxsize=1)
def version_info() -> dict[str, str]:
    return {
        VERSION_KEY: VERSION,
        REVISION_KEY: _git("rev-parse", "--short", "HEAD") or "unknown",
        BRANCH_KEY: _git("rev-parse", "--abbrev-ref", "HEAD") or "unknown",
        BUILD_USER_KEY: os.environ.get("USER", "unknown"),
    }


def inject(conf) -> None:
    """Stamp version keys into a TonyConf before it is frozen as final."""
    for k, v in version_info().items():
        conf.set(k, v)
