"""Job-archive shipping: distribute the staged job to remote executor hosts.

The reference uploads the src zip, python venv, and frozen config to HDFS
staging (TonyClient.java:232-315) and every container downloads + unpacks
them before the task starts (Utils.extractResources, util/Utils.java:758-771).
This is the rebuild's transport-agnostic equivalent for TPU fleets, where
there is no HDFS: the client tars the staged job dir (frozen config, src/,
resources/), optionally uploads it with a user-supplied command (gsutil on
GCP, scp on bare SSH clusters), and each executor fetches + unpacks the
archive into a host-local directory that then serves as its job dir.

Supported archive URIs on the fetch side:
  /abs/path or file://...   shared or local filesystem (cp)
  scp://host:/path          scp -o BatchMode=yes
  gs://bucket/key           gsutil cp (TPU VMs ship gsutil)
  http(s)://...             urllib
"""

from __future__ import annotations

import hashlib
import logging
import os
import shutil
import subprocess
import tarfile
import tempfile
import urllib.request
from pathlib import Path

log = logging.getLogger(__name__)

ARCHIVE_NAME = "job_archive.tar.gz"
# written next to the unpacked content recording the digest verified at
# unpack time, so the idempotent-reuse path can enforce it too
_DIGEST_MARKER = ".archive_sha256"
# client-staged content worth shipping; logs/workdir/events are runtime output
_SHIP_EXCLUDE = {"logs", "workdir", "driver.log", "driver_info.json",
                 ARCHIVE_NAME, "events"}


def build_job_archive(job_dir: str | Path) -> Path:
    """Tar the staged inputs of job_dir (frozen conf, src/, resources/) into
    <job_dir>/job_archive.tar.gz and return its path."""
    job_dir = Path(job_dir)
    out = job_dir / ARCHIVE_NAME
    with tarfile.open(out, "w:gz") as tf:
        for entry in sorted(job_dir.iterdir()):
            if entry.name in _SHIP_EXCLUDE:
                continue
            tf.add(entry, arcname=entry.name)
    return out


def sha256_file(path: str | Path) -> str:
    """Hex sha256 of a file, streamed."""
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def upload_archive(archive: Path, uri: str, upload_cmd: str) -> None:
    """Run the user-supplied upload command ({archive} and {uri} templates) —
    the HDFS-upload seam without baking in one cloud's CLI."""
    # token replace, not str.format: the command is arbitrary shell where
    # literal braces (${VAR}, awk '{...}') are ordinary syntax
    cmd = upload_cmd.replace("{archive}", str(archive)).replace("{uri}", uri)
    log.info("uploading job archive: %s", cmd)
    subprocess.run(cmd, shell=True, check=True, timeout=600)


def fetch_archive(uri: str, dest: Path) -> Path:
    """Fetch the archive at `uri` to local file `dest` (see module docstring
    for supported schemes)."""
    dest.parent.mkdir(parents=True, exist_ok=True)
    if uri.startswith("file://"):
        uri = uri[len("file://"):]
    if uri.startswith("scp://"):
        # scp://host:/path or scp://host:path
        rest = uri[len("scp://"):]
        host, _, path = rest.partition(":")
        if not host or not path:
            raise ValueError(f"bad scp uri (need scp://host:/path): {uri}")
        subprocess.run(
            ["scp", "-o", "BatchMode=yes", f"{host}:{path}", str(dest)],
            check=True, timeout=600,
        )
    elif uri.startswith("gs://"):
        subprocess.run(
            ["gsutil", "cp", uri, str(dest)], check=True, timeout=600
        )
    elif uri.startswith(("http://", "https://")):
        with urllib.request.urlopen(uri, timeout=600) as r, open(dest, "wb") as f:
            shutil.copyfileobj(r, f)
    else:
        shutil.copyfile(uri, dest)
    return dest


def localize_job(uri: str, app_id: str, base_dir: str | None = None,
                 sha256: str | None = None) -> str:
    """Executor side: fetch + unpack the job archive into a host-local
    directory and return it (the executor's job dir from then on) — reference
    Utils.extractResources (util/Utils.java:758-771).

    When `sha256` is given (frozen at submit time), the fetched bytes are
    verified BEFORE unpack and a mismatch raises — a tampered or truncated
    archive must never execute (the integrity role of the reference's
    kerberized HDFS staging, TonyClient.java:981-1030).

    Idempotent per (base, app_id): a directory that already holds the frozen
    config is reused, so multiple executors on one host fetch once."""
    from ..conf import FINAL_CONF_NAME

    base = Path(base_dir or os.environ.get("TONY_LOCAL_DIR", "")
                or Path(tempfile.gettempdir()) / "tony-localized")
    target = base / app_id
    final = target / FINAL_CONF_NAME
    marker = target / _DIGEST_MARKER
    if final.exists():
        # the reuse path must uphold the same integrity guarantee as a fresh
        # fetch: the unpacker records what it verified in a marker file, and
        # a digest-expecting caller refuses a dir localized without (or with
        # a different) verification rather than executing unchecked content
        if sha256:
            recorded = marker.read_text().strip() if marker.exists() else ""
            if recorded != sha256.lower():
                raise ValueError(
                    f"localized job dir {target} was unpacked from an archive "
                    f"with sha256 {recorded or '<unverified>'}, but this task "
                    f"expects {sha256} — refusing to reuse it"
                )
        log.info("job already localized at %s", target)
        return str(target)
    base.mkdir(parents=True, exist_ok=True)
    # tmp lives inside base so the final os.replace is a same-fs rename
    tmp = Path(tempfile.mkdtemp(prefix=f"{app_id}-fetch-", dir=str(base)))
    try:
        archive = fetch_archive(uri, tmp / ARCHIVE_NAME)
        if sha256:
            got = sha256_file(archive)
            if got != sha256.lower():
                raise ValueError(
                    f"job archive integrity check failed for {uri}: "
                    f"expected sha256 {sha256}, fetched {got} — refusing to "
                    f"unpack (tampered or truncated archive)"
                )
        unpack = tmp / "unpacked"
        unpack.mkdir()
        with tarfile.open(archive) as tf:
            try:
                tf.extractall(unpack, filter="data")
            except TypeError:  # Python < 3.10.12: no `filter` kwarg
                tf.extractall(unpack)
        if not (unpack / FINAL_CONF_NAME).exists():
            raise FileNotFoundError(
                f"archive at {uri} has no {FINAL_CONF_NAME} — not a job archive"
            )
        if sha256:
            (unpack / _DIGEST_MARKER).write_text(sha256.lower() + "\n")
        target.parent.mkdir(parents=True, exist_ok=True)
        try:
            os.replace(unpack, target)  # atomic: concurrent executors race safely
        except OSError:
            if not final.exists():  # lost the race AND nobody else won it
                raise
        log.info("localized job archive %s -> %s", uri, target)
        return str(target)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
