"""Containerized task launch: wrap the user command in `docker run`.

TPU-native stand-in for the reference's Docker-on-YARN support, where the
client injects `YARN_CONTAINER_RUNTIME_TYPE=docker`, the image, and mount
list into the container env and the NodeManager does the wrapping
(HadoopCompatibleAdapter.java:45-159; key names from
TonyConfigurationKeys.java:245-290). Here there is no NodeManager, so the
executor builds the `docker run` line itself:

- `--network host` keeps the rendezvous contract identical to a bare process
  (ports advertised to the driver remain reachable);
- `--user <uid>:<gid>` of the executor, so files written under the mounted
  job dir stay owned by the submitting user and an SO_REUSEPORT child rebind
  stays in the executor's reuseport group (Linux requires matching EUID);
  override with a later --user in `tony.docker.extra-args` if the image
  needs root;
- the job dir is bind-mounted at the same path, so TONY_JOB_DIR and the
  localized workdir resolve inside the container;
- the env contract is passed through explicitly with `-e` flags — the
  executor's own environment is host-specific and stays outside;
- `--name` is the task id, so the kill cascade can `docker rm -f` it (the
  docker CLI process does not forward SIGKILL to the container).
"""

from __future__ import annotations

import os
import subprocess
from typing import TYPE_CHECKING

if TYPE_CHECKING:
    from ..conf import TonyConf

from ..conf import keys as K


def container_enabled(conf: "TonyConf | None") -> bool:
    return bool(conf is not None and conf.get_bool(K.DOCKER_ENABLED, False))


def container_name(app_id: str, role: str, index: int) -> str:
    safe = "".join(c if c.isalnum() or c in "_.-" else "-" for c in app_id)
    return f"tony-{safe}-{role}-{index}"


def passthrough_env(conf: "TonyConf", role: str) -> dict[str, str]:
    """Vars the driver injects into the *executor's* environment that must
    follow the task into the container: `tony.execution.env` K=V pairs and
    the role's per-spec env (driver.py _task_env). In non-container mode the
    task inherits these via os.environ."""
    out: dict[str, str] = {}
    for kv in conf.get_list(K.EXECUTION_ENV):
        if "=" in kv:
            k, v = kv.split("=", 1)
            out[k] = v
    for spec in conf.role_specs():
        if spec.name == role:
            out.update(spec.env)
    return out


def build_container_command(
    command: str,
    env: dict[str, str],
    conf: "TonyConf",
    work_dir: str | None = None,
    role: str | None = None,
    job_dir: str | None = None,
    name: str | None = None,
) -> list[str]:
    """argv for running `command` inside the configured image.

    Mount entries are `src:dst[:ro]` strings. The job dir (which contains
    the per-task work dir) is bind-mounted so the TONY_JOB_DIR contract —
    frozen config, logs, checkpoints — holds inside; a per-role image
    (`tony.docker.<role>.image`) overrides the global one (reference
    getDockerImageKey, TonyConfigurationKeys.java:246-248).
    """
    image = conf.get(K.DOCKER_IMAGE, "")
    if role:
        image = conf.get(K.docker_image_key(role), image)
    if not image:
        raise ValueError(f"{K.DOCKER_ENABLED} is set but {K.DOCKER_IMAGE} is empty")
    argv = ["docker", "run", "--rm", "--network", "host",
            "--user", f"{os.getuid()}:{os.getgid()}"]
    if name:
        argv += ["--name", name]
    mount_root = job_dir or work_dir
    if mount_root:
        argv += ["-v", f"{mount_root}:{mount_root}"]
    if work_dir:
        argv += ["-w", work_dir]
    for mount in conf.get_list(K.DOCKER_MOUNTS):
        argv += ["-v", mount]
    for kv in sorted(env.items()):
        argv += ["-e", "=".join(kv)]
    argv += conf.get_list(K.DOCKER_RUN_ARGS)
    argv += [image, "bash", "-c", command]
    return argv


def remove_container(name: str) -> None:
    """Force-remove a (possibly already gone) container; the kill-cascade
    complement to --name. Never raises."""
    try:
        subprocess.run(
            ["docker", "rm", "-f", name],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            timeout=30, check=False,
        )
    except Exception:
        pass
