"""Shared utilities."""

from .localization import (
    ResourceSpec,
    localize_resources,
    parse_resources,
    stage_resources,
)

__all__ = [
    "ResourceSpec", "parse_resources", "stage_resources", "localize_resources",
]
