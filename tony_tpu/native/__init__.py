"""ctypes loader for the native runtime library (libtonytpu.so).

Builds lazily with `make` on first use if the toolchain is present; every
caller has a pure-Python fallback (metrics.py's /proc walk, cli/proxy.py's
threaded pump), so the framework works with or without the .so.
"""

from __future__ import annotations

import ctypes
import logging
import subprocess
import threading
from pathlib import Path

log = logging.getLogger(__name__)

_DIR = Path(__file__).parent
_LIB_PATH = _DIR / "libtonytpu.so"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_build_attempted = False


def _try_build() -> bool:
    global _build_attempted
    if _build_attempted:
        return _LIB_PATH.exists()
    _build_attempted = True
    try:
        subprocess.run(
            ["make", "-s"], cwd=_DIR, check=True, capture_output=True, timeout=120
        )
        return True
    except (OSError, subprocess.SubprocessError) as e:
        log.debug("native build unavailable: %s", e)
        return False


def get_lib() -> ctypes.CDLL | None:
    """The loaded library, or None when unavailable."""
    global _lib
    with _lock:
        if _lib is not None:
            return _lib
        if not _LIB_PATH.exists() and not _try_build():
            return None
        try:
            lib = ctypes.CDLL(str(_LIB_PATH))
        except OSError as e:
            log.warning("could not load %s: %s", _LIB_PATH, e)
            return None
        lib.tony_proc_tree_rss_mb.argtypes = [ctypes.c_int]
        lib.tony_proc_tree_rss_mb.restype = ctypes.c_double
        lib.tony_proxy_start.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
        lib.tony_proxy_start.restype = ctypes.c_int
        lib.tony_proxy_stop.argtypes = [ctypes.c_int]
        lib.tony_proxy_stop.restype = None
        _lib = lib
        return _lib


def proc_tree_rss_mb(root_pid: int) -> float | None:
    """Native process-tree RSS; None if the library is unavailable or the
    walk failed (caller falls back to the Python /proc walk)."""
    lib = get_lib()
    if lib is None:
        return None
    value = lib.tony_proc_tree_rss_mb(root_pid)
    return value if value >= 0 else None


class NativeProxy:
    """Epoll-based TCP proxy; same surface as cli.proxy.ProxyServer."""

    def __init__(self, remote_host: str, remote_port: int, local_port: int = 0):
        self._args = (remote_host, remote_port, local_port)
        self.local_port = -1

    @staticmethod
    def available() -> bool:
        return get_lib() is not None

    def start(self) -> None:
        lib = get_lib()
        if lib is None:
            raise RuntimeError("native library unavailable")
        host, port, local = self._args
        self.local_port = lib.tony_proxy_start(host.encode(), port, local)
        if self.local_port < 0:
            raise OSError("native proxy failed to start")

    def stop(self) -> None:
        lib = get_lib()
        if lib is not None and self.local_port > 0:
            lib.tony_proxy_stop(self.local_port)
            self.local_port = -1
