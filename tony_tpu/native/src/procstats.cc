// Process-tree resource sampling for the executor metrics loop.
//
// Plays the role of the reference's YARN ResourceCalculatorProcessTree walk
// (used by TaskMonitor.java:101-170) — implemented natively so the 5s metrics
// tick costs microseconds instead of a Python directory walk over /proc.
//
// Exposed via ctypes from tony_tpu/native/__init__.py.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <dirent.h>
#include <vector>

namespace {

struct ProcInfo {
  int pid;
  int ppid;
  int64_t rss_kb;
};

// Parse /proc/<pid>/stat for ppid and /proc/<pid>/status for VmRSS.
// stat field 4 is ppid, but comm (field 2) may contain spaces/parens —
// scan from the last ')'.
bool read_proc(int pid, ProcInfo *out) {
  char path[64];
  std::snprintf(path, sizeof(path), "/proc/%d/stat", pid);
  FILE *f = std::fopen(path, "r");
  if (!f) return false;
  char buf[1024];
  size_t n = std::fread(buf, 1, sizeof(buf) - 1, f);
  std::fclose(f);
  if (n == 0) return false;
  buf[n] = '\0';
  const char *close_paren = std::strrchr(buf, ')');
  if (!close_paren) return false;
  int ppid = -1;
  char state;
  if (std::sscanf(close_paren + 1, " %c %d", &state, &ppid) != 2) return false;

  int64_t rss_kb = 0;
  std::snprintf(path, sizeof(path), "/proc/%d/status", pid);
  f = std::fopen(path, "r");
  if (f) {
    char line[256];
    while (std::fgets(line, sizeof(line), f)) {
      if (std::strncmp(line, "VmRSS:", 6) == 0) {
        rss_kb = std::atoll(line + 6);
        break;
      }
    }
    std::fclose(f);
  }
  out->pid = pid;
  out->ppid = ppid;
  out->rss_kb = rss_kb;
  return true;
}

}  // namespace

extern "C" {

// Sum of VmRSS over root_pid and all its descendants, in MiB.
// Returns -1.0 on error.
double tony_proc_tree_rss_mb(int root_pid) {
  DIR *proc = opendir("/proc");
  if (!proc) return -1.0;
  std::vector<ProcInfo> procs;
  procs.reserve(512);
  struct dirent *ent;
  while ((ent = readdir(proc)) != nullptr) {
    const char *name = ent->d_name;
    bool numeric = name[0] != '\0';
    for (const char *c = name; *c; ++c) {
      if (*c < '0' || *c > '9') { numeric = false; break; }
    }
    if (!numeric) continue;
    ProcInfo info;
    if (read_proc(std::atoi(name), &info)) procs.push_back(info);
  }
  closedir(proc);

  // BFS from root over the ppid edges; O(n^2) worst case on a few hundred
  // pids is well under a millisecond.
  std::vector<int> frontier{root_pid};
  std::vector<char> in_tree(procs.size(), 0);
  int64_t total_kb = 0;
  bool found_root = false;
  while (!frontier.empty()) {
    int pid = frontier.back();
    frontier.pop_back();
    for (size_t i = 0; i < procs.size(); ++i) {
      if (in_tree[i]) continue;
      if (procs[i].pid == pid) {
        in_tree[i] = 1;
        total_kb += procs[i].rss_kb;
        if (pid == root_pid) found_root = true;
      } else if (procs[i].ppid == pid) {
        in_tree[i] = 1;
        total_kb += procs[i].rss_kb;
        frontier.push_back(procs[i].pid);
      }
    }
  }
  if (!found_root) return -1.0;
  return static_cast<double>(total_kb) / 1024.0;
}

}  // extern "C"
