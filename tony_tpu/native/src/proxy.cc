// Epoll-based TCP proxy — the native version of tony_tpu/cli/proxy.py.
//
// The reference's tony-proxy is a thread-per-connection Java byte pump
// (tony-proxy/.../ProxyServer.java:41-90). This one multiplexes every
// connection pair on a single epoll loop: O(1) threads, no GIL, suitable for
// fronting a notebook or TensorBoard from a TPU host.
//
// C API (ctypes):
//   int  tony_proxy_start(const char* remote_host, int remote_port,
//                         int local_port);   // returns bound local port, <0 on error
//   void tony_proxy_stop(int local_port);

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <map>
#include <mutex>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

constexpr size_t kBuf = 1 << 16;

int set_nonblock(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

struct Conn {
  int peer = -1;
  std::vector<uint8_t> pending;  // bytes to write to THIS fd
  bool peer_closed = false;
};

class Proxy {
 public:
  Proxy(std::string rhost, int rport) : rhost_(std::move(rhost)), rport_(rport) {}

  int start(int local_port) {
    listener_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listener_ < 0) return -1;
    int one = 1;
    setsockopt(listener_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = htons(static_cast<uint16_t>(local_port));
    if (bind(listener_, reinterpret_cast<sockaddr *>(&addr), sizeof(addr)) < 0 ||
        listen(listener_, 64) < 0) {
      close(listener_);
      return -1;
    }
    socklen_t len = sizeof(addr);
    getsockname(listener_, reinterpret_cast<sockaddr *>(&addr), &len);
    port_ = ntohs(addr.sin_port);

    stop_fd_ = eventfd(0, EFD_NONBLOCK);
    epfd_ = epoll_create1(0);
    set_nonblock(listener_);
    add_fd(listener_, EPOLLIN);
    add_fd(stop_fd_, EPOLLIN);
    thread_ = std::thread([this] { loop(); });
    return port_;
  }

  void stop() {
    uint64_t one = 1;
    ssize_t ignored = write(stop_fd_, &one, sizeof(one));
    (void)ignored;
    if (thread_.joinable()) thread_.join();
  }

  int port() const { return port_; }

 private:
  void add_fd(int fd, uint32_t events) {
    epoll_event ev{};
    ev.events = events;
    ev.data.fd = fd;
    epoll_ctl(epfd_, EPOLL_CTL_ADD, fd, &ev);
  }

  void mod_fd(int fd, uint32_t events) {
    epoll_event ev{};
    ev.events = events;
    ev.data.fd = fd;
    epoll_ctl(epfd_, EPOLL_CTL_MOD, fd, &ev);
  }

  int connect_upstream() {
    addrinfo hints{}, *res = nullptr;
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    if (getaddrinfo(rhost_.c_str(), std::to_string(rport_).c_str(), &hints,
                    &res) != 0 || res == nullptr) {
      return -1;
    }
    int fd = ::socket(res->ai_family, res->ai_socktype, res->ai_protocol);
    if (fd >= 0 && connect(fd, res->ai_addr, res->ai_addrlen) < 0) {
      close(fd);
      fd = -1;
    }
    freeaddrinfo(res);
    if (fd >= 0) {
      set_nonblock(fd);
      int one = 1;
      setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    }
    return fd;
  }

  void close_pair(int fd) {
    auto it = conns_.find(fd);
    if (it == conns_.end()) return;
    int peer = it->second.peer;
    epoll_ctl(epfd_, EPOLL_CTL_DEL, fd, nullptr);
    close(fd);
    conns_.erase(it);
    auto pit = conns_.find(peer);
    if (pit != conns_.end()) {
      epoll_ctl(epfd_, EPOLL_CTL_DEL, peer, nullptr);
      close(peer);
      conns_.erase(pit);
    }
  }

  void pump(int src) {
    auto sit = conns_.find(src);
    if (sit == conns_.end()) return;
    int dst = sit->second.peer;
    auto dit = conns_.find(dst);
    if (dit == conns_.end()) { close_pair(src); return; }

    uint8_t buf[kBuf];
    for (;;) {
      ssize_t n = recv(src, buf, sizeof(buf), 0);
      if (n > 0) {
        size_t off = 0;
        if (dit->second.pending.empty()) {
          ssize_t w = send(dst, buf, static_cast<size_t>(n), MSG_NOSIGNAL);
          if (w < 0 && errno != EAGAIN && errno != EWOULDBLOCK) {
            close_pair(src);
            return;
          }
          off = w > 0 ? static_cast<size_t>(w) : 0;
        }
        if (off < static_cast<size_t>(n)) {
          auto &p = dit->second.pending;
          p.insert(p.end(), buf + off, buf + n);
          mod_fd(dst, EPOLLIN | EPOLLOUT);
        }
      } else if (n == 0) {
        close_pair(src);
        return;
      } else {
        if (errno == EAGAIN || errno == EWOULDBLOCK) return;
        close_pair(src);
        return;
      }
    }
  }

  void flush(int fd) {
    auto it = conns_.find(fd);
    if (it == conns_.end()) return;
    auto &p = it->second.pending;
    while (!p.empty()) {
      ssize_t w = send(fd, p.data(), p.size(), MSG_NOSIGNAL);
      if (w > 0) {
        p.erase(p.begin(), p.begin() + w);
      } else if (errno == EAGAIN || errno == EWOULDBLOCK) {
        return;
      } else {
        close_pair(fd);
        return;
      }
    }
    mod_fd(fd, EPOLLIN);
  }

  void loop() {
    epoll_event events[64];
    for (;;) {
      int n = epoll_wait(epfd_, events, 64, 1000);
      for (int i = 0; i < n; ++i) {
        int fd = events[i].data.fd;
        if (fd == stop_fd_) goto done;
        if (fd == listener_) {
          for (;;) {
            int client = accept(listener_, nullptr, nullptr);
            if (client < 0) break;
            int upstream = connect_upstream();
            if (upstream < 0) { close(client); continue; }
            set_nonblock(client);
            int one = 1;
            setsockopt(client, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
            conns_[client] = Conn{upstream, {}, false};
            conns_[upstream] = Conn{client, {}, false};
            add_fd(client, EPOLLIN);
            add_fd(upstream, EPOLLIN);
          }
          continue;
        }
        if (events[i].events & EPOLLOUT) flush(fd);
        if (events[i].events & (EPOLLIN | EPOLLHUP | EPOLLERR)) pump(fd);
      }
    }
  done:
    for (auto &kv : conns_) close(kv.first);
    conns_.clear();
    close(listener_);
    close(epfd_);
    close(stop_fd_);
  }

  std::string rhost_;
  int rport_;
  int listener_ = -1;
  int epfd_ = -1;
  int stop_fd_ = -1;
  int port_ = -1;
  std::thread thread_;
  std::map<int, Conn> conns_;
};

std::mutex g_mu;
std::map<int, Proxy *> g_proxies;

}  // namespace

extern "C" {

int tony_proxy_start(const char *remote_host, int remote_port, int local_port) {
  auto *p = new Proxy(remote_host, remote_port);
  int port = p->start(local_port);
  if (port < 0) {
    delete p;
    return -1;
  }
  std::lock_guard<std::mutex> lk(g_mu);
  g_proxies[port] = p;
  return port;
}

void tony_proxy_stop(int local_port) {
  Proxy *p = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_proxies.find(local_port);
    if (it == g_proxies.end()) return;
    p = it->second;
    g_proxies.erase(it);
  }
  p->stop();
  delete p;
}

}  // extern "C"
