"""Fleet router: the front door of a driver-orchestrated serving fleet.

The reference fronts long-lived services with a dumb TCP proxy
(tony-proxy/.../ProxyServer.java:27-39); at fleet scale the front door
has to be smarter, because everything that makes one SlotServer fast is
LOCAL to a replica: the prefix KV cache (PR 2) only hits if requests
sharing a template keep landing on the same server, queue depth and
Retry-After (PR 3/4) describe one engine's backlog, and /healthz
describes one loop. This module composes those shipped signals into a
load balancer:

- **Prefix-affinity routing.** The first ``prefill_chunk``-aligned
  blocks of the prompt hash to a routing key; rendezvous hashing
  (highest-random-weight over replica NAMES, so a replica restart with
  a new port keeps its templates and an ejection remaps only its own
  keys) makes every request of a template sticky to one replica — the
  replica whose trie actually holds that template's KV. When the sticky
  replica is saturated, the request SPILLS to the next choice in
  rendezvous order: a warm cache is worth a queued beat, not a missed
  deadline. Prompts shorter than one chunk (nothing cacheable) route
  least-loaded by queue depth + active slots from each replica's /stats.
- **429-aware retry.** A shed replica's ``Retry-After`` (the engine's
  EWMA service-rate estimate) marks it saturated for that window; the
  router immediately tries the next candidate, and only when EVERY live
  replica is backpressuring does it sleep — a jittered fraction of the
  smallest advertised Retry-After — before re-ranking. Transport errors
  and 5xx EJECT the replica on the spot and retry elsewhere with
  jittered exponential backoff, so a replica killed mid-request costs
  latency, never a failed request (the driver restarts it under budget;
  discovery re-adds it at its new port).
- **Replay-aware failover.** Every routed request carries a
  ``progress_key``; the health loop batch-polls each replica's
  ``GET /progress`` for the router's outstanding requests and journals
  the emitted-so-far prefix per request. On a transport failure/5xx
  mid-request the router re-asks the failed replica once (a 5xx
  replica is often still alive; a SIGKILLed one refuses fast and the
  journaled prefix stands) and resubmits to the rendezvous runner-up
  with ``resume_tokens`` — the replacement replica teacher-forces the
  prefix through its prefill path and resumes decoding, so the client
  still receives the FULL stream (byte-identical for greedy requests)
  and the dead replica's decode work is not re-decoded from scratch
  (docs/serving.md "Request durability & replay";
  ``router_failovers_total``).
- **Ejection / readmission.** A health thread probes every replica's
  /healthz (eject after ``eject_after`` consecutive failures, readmit
  on the first success), refreshes /stats (queue depth, slots,
  retry_after), and — when constructed over a driver (``discover``) —
  re-syncs the replica set from ``get_task_infos``: the driver's
  heartbeat-liveness view plus the ``serve_port`` each replica
  published via the publish_ports RPC (runtimes/serving.py).
- **Observability.** Per-request ``RequestTrace``s (``submitted ->
  routed -> finished|shed|failed``, with replica/retry attrs) feed an
  optional trace sink, and GET /metrics renders the ``router_*``
  families (docs/observability.md "Router metrics") through the shared
  PromRenderer.

- **Streaming pass-through.** ``generate(on_tokens=...)`` relays a
  replica's SSE stream delta-by-delta, HARVESTING the emitted prefix
  from the stream itself as the failover resume state (fresher than
  any /progress poll, which remains the fallback for non-streamed
  requests). A replica dying mid-stream triggers the normal
  eject+failover; the replacement re-streams from position 0 and the
  absolute-position dedupe forwards each token exactly once
  (``router_stream_failovers_total``). Affinity keys are per
  ``(model, template)`` — two models sharing a prompt template hash to
  different rendezvous buckets, since each engine owns its own prefix
  pool.

``python -m tony_tpu.cli.main route`` serves the HTTP front door:
POST /generate (the serve contract, proxied, ``stream=true``
relayed), the OpenAI-compatible POST /v1/completions +
/v1/chat/completions (one URL fronts the whole fleet), GET /healthz,
/stats, /metrics. See docs/serving.md "Fleet serving" and "Streaming &
OpenAI compatibility".
"""

from __future__ import annotations

import argparse
import hashlib
import itertools
import json
import logging
import random
import threading
import time
import urllib.error
import urllib.request

from . import metrics as _metrics
from .observability import (
    PROM_CONTENT_TYPE,
    TRACE_HEADER,
    TRACE_ID_RESPONSE_HEADER,
    Histogram,
    PromRenderer,
    RequestTrace,
    TraceContext,
)

log = logging.getLogger(__name__)


class RouterError(RuntimeError):
    """The router could not complete the request."""


class NoReplicaError(RouterError):
    """No live replica in the fleet (all ejected / none discovered) —
    or, for a request naming a model, no live replica ADVERTISES that
    model (model-aware routing: replicas publish their registry on
    /stats)."""


class RouterClientError(RouterError):
    """The replica rejected the request as malformed (4xx other than
    429) — the client's fault, not the replica's: no retry, no
    ejection, surfaced as HTTP 400."""


class StreamConsumerError(RouterError):
    """The STREAMING CLIENT vanished (or its callback failed) while the
    router relayed a replica's stream. Not a replica fault: no retry,
    no ejection — the downstream connection is closed (the replica's
    own disconnect detection cancels the request) and the front door
    counts a ``router_stream_disconnects_total``."""


class FleetSaturatedError(RouterError):
    """Every live replica is shedding (429); carries the smallest
    advertised Retry-After so the front door can forward honest
    backpressure instead of inventing a constant."""

    def __init__(self, msg: str, retry_after_s: int = 1):
        super().__init__(msg)
        self.retry_after_s = max(1, int(retry_after_s))


class _ReplicaShed(Exception):
    """Internal: one replica answered 429."""

    def __init__(self, retry_after_s: int):
        super().__init__(f"shed with Retry-After {retry_after_s}s")
        self.retry_after_s = max(1, int(retry_after_s))


class _ReplicaUnavailable(Exception):
    """Internal: transport error / 5xx from one replica.
    ``never_sent`` marks a connection REFUSED — the request never
    reached the replica, so the retry is an ordinary re-route, not a
    mid-request failover (the distinction keeps
    ``router_failovers_total`` an honest mid-stream-recovery count)."""

    def __init__(self, msg: str, never_sent: bool = False):
        super().__init__(msg)
        self.never_sent = never_sent


class _ReplicaTimeout(Exception):
    """Internal: the POST hit the CALLER's deadline. Not evidence the
    replica is broken — a slow generation against an impatient client
    must not eject a healthy replica from everyone's rotation."""


class _ReplicaClientError(Exception):
    """Internal: one replica answered 4xx (other than 429) — the
    request itself is bad; retrying elsewhere would just repeat it."""


class Replica:
    """Router-side state of one backend SlotServer."""

    def __init__(self, name: str, host: str, port: int):
        self.name = name          # stable identity (task_id); the
        self.host = host          # rendezvous-hash input, so a restart
        self.port = port          # at a new port keeps its templates
        self.up = True            # optimistic: discovery only hands out
        self.consecutive_fails = 0  # endpoints that passed /healthz once
        self.saturated_until = 0.0  # monotonic 429-backpressure window
        self.retry_after_s = 1
        self.queued = 0
        self.active = 0
        self.slots = 0
        self.max_queue = 0
        # the models this replica advertises on /stats ("models" keys).
        # Empty = unknown/legacy replica: serves any model (requests
        # naming one still route here rather than failing a fleet that
        # predates multi-model /stats)
        self.models: set[str] = set()
        # disaggregated-serving role from /stats ("prefill" | "decode" |
        # "both"; docs/serving.md "Disaggregated serving"). Legacy
        # replicas that advertise none default to "both" — a mixed or
        # roleless fleet routes exactly as before
        self.role = "both"
        # the replica's own cumulative TTFT p99 from its newest /stats
        # poll (latency.ttft_s.p99_s) — rolled into stats()["fleet"],
        # the autoscale controller's router-side signal
        self.ttft_p99_s = 0.0
        # posts the ROUTER currently has outstanding against this
        # replica — exact and instantaneous, unlike the polled /stats
        # (which lag a health interval and double-count router traffic);
        # the load signal for least-loaded picks and saturation spill
        self.inflight = 0
        # counters (the per-replica /metrics families)
        self.requests = 0         # posts attempted against this replica
        self.retries = 0          # posts that were re-attempts
        self.shed = 0             # 429 answers received
        self.errors = 0           # transport errors / 5xx
        self.ejections = 0

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    @property
    def load(self) -> int:
        """Router-outstanding posts plus the polled engine queue — the
        queue captures traffic from OTHER clients/routers, inflight
        captures this router's own (fresher than any poll)."""
        return self.inflight + max(0, self.queued)


class FleetRouter:
    """Load balancer over N SlotServer replicas. Thread-safe: many HTTP
    handler threads call ``generate`` concurrently; one health thread
    (``start()``) maintains liveness, stats, and the replica set."""

    def __init__(self, replicas=(), *, prefill_chunk: int = 128,
                 affinity: bool = True, health_interval_s: float = 0.5,
                 eject_after: int = 2, spill_queue_depth: int | None = None,
                 probe_timeout_s: float = 2.0, stats_every: int = 4,
                 discover=None, trace_sink=None, seed: int | None = None,
                 discovery_grace_s: float = 10.0,
                 stats_phase: int | None = None):
        """``replicas``: static endpoints ("host:port" strings or
        (name, host, port) triples). ``discover``: zero-arg callable
        returning the current [(name, host, port)] — the driver-backed
        fleet view (see DriverDiscovery); called from the health loop,
        its result REPLACES the replica set — except during a
        control-plane outage: a discovery FAILURE (driver.json missing,
        RPC refused — the driver is dead or mid-recovery) keeps the
        last-known fleet serving and raises the
        ``router_discovery_stale`` gauge, and an implausible EMPTY
        result while live replicas still answer their own probes is
        distrusted for ``discovery_grace_s`` before the drop is
        honored (a freshly recovered driver may answer before its
        state is whole). ``spill_queue_depth``: treat
        a replica with that many queued requests as saturated even
        before it sheds (None = only trust 429s and the replica's own
        max_queue from /stats). ``stats_every``: refresh each replica's
        /stats only every Nth health tick — a /stats render takes the
        replica's serving lock and computes histogram quantiles, and
        polling it at liveness cadence measurably steals saturated
        replicas' cycles (the router's own in-flight counts carry the
        fast load signal between refreshes). ``stats_phase``: which
        tick (mod ``stats_every``) pulls /stats — None derives a
        per-INSTANCE phase from the router nonce, so N shared-nothing
        routers spread their /stats renders across the cycle instead
        of phase-locking N serving-lock grabs onto the same beat."""
        self.prefill_chunk = max(1, int(prefill_chunk))
        self.affinity = affinity
        self.health_interval_s = health_interval_s
        self.eject_after = max(1, int(eject_after))
        self.spill_queue_depth = spill_queue_depth
        self.probe_timeout_s = probe_timeout_s
        self.stats_every = max(1, int(stats_every))
        self._tick = 0
        self.discover = discover
        self.discovery_grace_s = float(discovery_grace_s)
        # control-plane-outage visibility: True while the router serves
        # its LAST-KNOWN fleet because discovery is failing (or handed
        # back an implausible empty set inside the grace window)
        self.discovery_stale = False
        self._discovery_empty_since: float | None = None
        self.trace_sink = trace_sink
        self._lock = threading.Lock()
        self._rng = random.Random(seed)
        self.replicas: dict[str, Replica] = {}
        for spec in replicas:
            if isinstance(spec, str):
                host, _, port = spec.rpartition(":")
                self._add_locked(spec, host or "127.0.0.1", int(port))
            else:
                name, host, port = spec
                self._add_locked(str(name), host, int(port))
        # router-local request ids: the replica assigns its own engine
        # ids; the router's trace needs an identity that survives retries
        self._ids = itertools.count()
        self.routing_hist = Histogram(lo=1e-6, hi=1.0)
        self.e2e_hist = Histogram()
        # where router-attributed fleet time goes, one histogram per
        # leg (``router_leg_seconds{leg=...}``): "relay" = the classic
        # single-replica POST, "prefill" = disagg leg 1, "transfer" =
        # leg-2 submit -> first relayed frame (payload ship + install),
        # "decode" = the rest (buffered leg 2 books entirely as decode)
        self.leg_hists = {leg: Histogram()
                          for leg in ("prefill", "transfer", "decode",
                                      "relay")}
        self.requests_total = 0
        self.failed_total = 0
        self.shed_total = 0           # requests the ROUTER gave up on (429)
        self.affinity_requests = 0    # requests that had a routing key
        self.affinity_hits = 0        # ... served by their sticky replica
        # replay-aware failover state: which replica each in-flight
        # request is posted to, and the freshest emitted prefix the
        # /progress polls have journaled for it (module docstring).
        # The nonce namespaces this router INSTANCE's progress keys so
        # a restarted router (or a shared-nothing peer) can't read
        # another router's requests — it must be unique per instance,
        # so it comes from OS entropy, never from ``seed`` (two routers
        # built with the same seed would otherwise collide key-for-key
        # and could splice each other's tokens into a failover resume).
        self._outstanding: dict[int, str] = {}      # rid -> replica name
        self._resume: dict[int, list[int]] = {}     # rid -> emitted prefix
        self._nonce = f"{random.SystemRandom().getrandbits(48):012x}"
        # client-supplied request ids make the progress key PORTABLE
        # across routers (``req:<id>``): a front-door retry through a
        # surviving router can harvest the prefix the dead router's
        # request journaled on the owning replica. rid -> portable key;
        # absent = the nonce-namespaced private key.
        self._pkeys: dict[int, str] = {}
        self.failovers_total = 0      # mid-request resubmissions elsewhere
        self.resumed_tokens_total = 0  # prefix tokens carried by failovers
        # disaggregated serving (docs/serving.md "Disaggregated
        # serving"): requests that attempted the two-leg prefill->decode
        # path, handoffs that completed through a KV import, and
        # attempts that fell back to the classic single-leg path
        # (either leg failed/torn — the fallback re-prefills from the
        # prompt, so disaggregation costs recompute, never a request)
        self.disagg_requests = 0
        self.disagg_handoffs = 0
        self.disagg_fallbacks = 0
        # streaming pass-through (docs/serving.md "Streaming & OpenAI
        # compatibility"): live relayed streams, tokens forwarded,
        # mid-stream failovers (resume prefix harvested from the relayed
        # stream itself), and front-door clients that vanished mid-relay
        self.streams_active = 0
        self.streamed_tokens_total = 0
        self.stream_failovers_total = 0
        self.stream_disconnects_total = 0
        # requests currently being relayed through THIS router
        # (buffered and streamed alike) — the router-tier saturation
        # signal the autoscaler scrapes (``router_relay_inflight``),
        # and the drain gate a SIGTERM waits on
        self._relay_inflight = 0
        # True once a drain began: new front-door requests are refused
        # (503, so an upstream LB moves on) while in-flight relays
        # finish — router scale-down is zero-dropped by construction
        self.draining = False
        # per-INSTANCE phase jitter (Heartbeater precedent,
        # executor.py): OS-entropy seeded, deliberately NOT ``seed`` —
        # N routers built alike must still desynchronize their health
        # polls, discovery reads, and /stats scrapes
        self._phase_rng = random.Random()
        self._stats_phase = (stats_phase if stats_phase is not None
                             else int(self._nonce, 16)) % self.stats_every
        self._stop = threading.Event()
        self._health_started = False
        self._health_thread: threading.Thread | None = None

    # ------------------------------------------------------------ replica set
    def _add_locked(self, name: str, host: str, port: int) -> Replica:
        rep = Replica(name, host, port)
        self.replicas[name] = rep
        return rep

    def sync_replicas(self, found: list[tuple[str, str, int]]) -> None:
        """Adopt a discovery result: add new replicas, re-point renamed
        endpoints (a restarted replica publishes a fresh port under the
        same task_id), drop replicas discovery no longer lists (killed /
        mid-restart — the driver's liveness view)."""
        with self._lock:
            seen = set()
            for name, host, port in found:
                name = str(name)
                seen.add(name)
                rep = self.replicas.get(name)
                if rep is None:
                    log.info("router: replica %s joined at %s:%d",
                             name, host, port)
                    self._add_locked(name, host, int(port))
                elif (rep.host, rep.port) != (host, int(port)):
                    log.info("router: replica %s moved %s:%d -> %s:%d",
                             name, rep.host, rep.port, host, port)
                    rep.host, rep.port = host, int(port)
                    rep.up = True           # a fresh endpoint, fresh chance
                    rep.consecutive_fails = 0
                    rep.saturated_until = 0.0
            for name in set(self.replicas) - seen:
                log.info("router: replica %s left the fleet", name)
                self.replicas.pop(name, None)

    # ----------------------------------------------------------------- health
    def start(self) -> None:
        """Start the health/discovery loop (idempotent)."""
        if self._health_thread is None or not self._health_thread.is_alive():
            self._stop.clear()
            self._health_started = True
            self._health_thread = threading.Thread(
                target=self._health_loop, name="router-health", daemon=True)
            self._health_thread.start()

    def shutdown(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5)

    def begin_drain(self) -> None:
        """Stop accepting NEW front-door requests (the HTTP handler
        503s them and ``/healthz`` goes unhealthy so an upstream LB
        ejects this router) while in-flight relays keep running."""
        with self._lock:
            if not self.draining:
                log.info("router: draining (%d relay(s) in flight)",
                         self._relay_inflight)
            self.draining = True

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Drain for scale-down/roll (mirrors serve's SIGTERM
        contract): refuse new requests, wait up to ``timeout_s`` for
        every in-flight relay — buffered and streamed — to finish.
        True when the router emptied; False when the timeout cut the
        wait short (the stragglers are abandoned with the process)."""
        self.begin_drain()
        deadline = time.monotonic() + max(0.0, timeout_s)
        while True:
            with self._lock:
                if self._relay_inflight == 0:
                    return True
            if time.monotonic() >= deadline:
                with self._lock:
                    log.warning(
                        "router: drain timed out with %d relay(s) "
                        "still in flight", self._relay_inflight)
                    return self._relay_inflight == 0
            time.sleep(0.05)

    def _health_loop(self) -> None:
        # ±10% phase jitter per wait (Heartbeater precedent): N
        # shared-nothing routers started together must not probe every
        # replica's /healthz — or hit discovery — in lockstep waves
        while not self._stop.wait(self.health_interval_s
                                  * self._phase_rng.uniform(0.9, 1.1)):
            try:
                self.health_tick()
            except Exception:       # the loop must outlive a bad tick
                log.exception("router health tick failed")

    def health_tick(self) -> None:
        """One maintenance pass: discovery re-sync, then per-replica
        /healthz probe (eject after ``eject_after`` consecutive
        failures, readmit on the first success) + /stats refresh every
        ``stats_every``-th tick (see __init__)."""
        self._tick += 1
        # the FIRST tick always refreshes (fresh routers need a baseline
        # before any traffic), then every stats_every-th at this
        # router's own phase offset (see __init__: staggered so N
        # routers don't grab every replica's serving lock on one beat)
        refresh_stats = (self.stats_every == 1 or self._tick == 1
                         or (self._tick % self.stats_every)
                         == self._stats_phase)
        if self.discover is not None:
            self._discovery_tick()
        with self._lock:
            reps = list(self.replicas.values())
        for rep in reps:
            healthy = self._probe_healthz(rep)
            with self._lock:
                if rep.name not in self.replicas:
                    continue        # discovery removed it mid-probe
                if healthy:
                    rep.consecutive_fails = 0
                    if not rep.up:
                        log.info("router: readmitting %s", rep.name)
                        rep.up = True
                else:
                    rep.consecutive_fails += 1
                    if rep.up and rep.consecutive_fails >= self.eject_after:
                        self._eject_locked(rep, "healthz")
            if healthy and refresh_stats:
                self._refresh_stats(rep)
        self._refresh_progress(reps)

    def _discovery_tick(self) -> None:
        """Re-sync the replica set from discovery, tolerating a
        control-plane outage: a failed call (driver dead / driver.json
        stale / RPC refused) keeps the last-known fleet serving — the
        replicas are still answering their own /healthz probes — and an
        EMPTY result while live replicas exist is distrusted for
        ``discovery_grace_s`` (a recovering driver can answer before
        its journal replay restored the published ports). Either way
        ``discovery_stale`` (and the ``router_discovery_stale`` gauge)
        says the router is flying blind."""
        try:
            found = list(self.discover())
        except Exception as e:
            # a flapping/dead driver RPC must not tear the fleet down;
            # the last known replica set keeps serving
            if not self.discovery_stale:
                log.warning("router discovery failed (%s); serving the "
                            "last-known fleet", e)
            self.discovery_stale = True
            return
        with self._lock:
            live = sum(r.up for r in self.replicas.values())
        if not found and live:
            now = time.monotonic()
            if self._discovery_empty_since is None:
                self._discovery_empty_since = now
            if now - self._discovery_empty_since < self.discovery_grace_s:
                if not self.discovery_stale:
                    log.warning(
                        "router discovery reports an EMPTY fleet while "
                        "%d replica(s) still answer; distrusting it for "
                        "%.1fs", live, self.discovery_grace_s)
                self.discovery_stale = True
                return
            # the driver has insisted for the whole grace: honor it
            log.warning("router discovery empty past the %.1fs grace; "
                        "dropping the fleet", self.discovery_grace_s)
        else:
            self._discovery_empty_since = None
        self.sync_replicas(found)
        if self.discovery_stale:
            log.info("router discovery recovered (%d replica(s))",
                     len(found))
        self.discovery_stale = False

    def _pkey(self, rid: int) -> str:
        # a client-supplied request_id makes the key portable across
        # routers (req:<id>); otherwise the nonce namespaces it to this
        # instance so shared-nothing peers can't splice each other's
        # tokens into a resume
        return self._pkeys.get(rid) or f"{self._nonce}:{rid}"

    def _refresh_progress(self, reps) -> None:
        """Journal the emitted-so-far prefix of every request this
        router has outstanding (batched GET /progress per replica,
        best-effort — a replica without the endpoint just yields
        nothing). The journaled prefix is what a failover resume
        carries when the serving replica dies mid-request; staleness
        only costs re-decode of the gap, never correctness (any true
        prefix replays exactly)."""
        with self._lock:
            by_rep: dict[str, list[int]] = {}
            for rid, name in self._outstanding.items():
                by_rep.setdefault(name, []).append(rid)
        for rep in reps:
            rids = by_rep.get(rep.name)
            if not rids:
                continue
            got = self._fetch_progress(rep, [self._pkey(r) for r in rids])
            if not got:
                continue
            with self._lock:
                for rid in rids:
                    if self._outstanding.get(rid) != rep.name:
                        # finished while we polled (a write would leak
                        # the entry _seal already popped), or failed
                        # over to ANOTHER replica mid-poll (a stale
                        # answer from the abandoned replica could
                        # contain a diverging sampled continuation —
                        # only the CURRENT replica's stream is a true
                        # prefix)
                        continue
                    toks = (got.get(self._pkey(rid)) or {}).get("tokens")
                    if toks and len(toks) > len(self._resume.get(rid, ())):
                        self._resume[rid] = [int(t) for t in toks]

    def _fetch_progress(self, rep: Replica, keys,
                        timeout: float | None = None) -> dict:
        """Best-effort GET /progress?keys=... against one replica."""
        if not keys:
            return {}
        url = rep.base_url + "/progress?keys=" + ",".join(keys)
        try:
            with urllib.request.urlopen(
                    url, timeout=timeout or self.probe_timeout_s) as r:
                got = json.loads(r.read().decode())
                return got if isinstance(got, dict) else {}
        except Exception:
            return {}

    def _probe_healthz(self, rep: Replica) -> bool:
        try:
            with urllib.request.urlopen(rep.base_url + "/healthz",
                                        timeout=self.probe_timeout_s) as r:
                return r.status == 200
        except Exception:
            return False

    def _refresh_stats(self, rep: Replica) -> None:
        """Pull the load signals the picker uses (best-effort)."""
        try:
            with urllib.request.urlopen(rep.base_url + "/stats",
                                        timeout=self.probe_timeout_s) as r:
                st = json.loads(r.read().decode())
        except Exception:
            return
        with self._lock:
            rep.queued = int(st.get("queued", 0) or 0)
            rep.active = int(st.get("active", 0) or 0)
            rep.slots = int(st.get("slots", 0) or 0)
            rep.max_queue = int(st.get("max_queue", 0) or 0)
            rep.retry_after_s = int(st.get("retry_after_s", 1) or 1)
            role = st.get("role")
            if role in ("prefill", "decode", "both"):
                rep.role = role
            models = st.get("models")
            if isinstance(models, dict):
                rep.models = {str(m) for m in models}
            elif isinstance(models, (list, tuple)):
                rep.models = {str(m) for m in models}
            try:
                rep.ttft_p99_s = float(
                    (st.get("latency") or {}).get("ttft_s", {})
                    .get("p99_s", 0.0) or 0.0)
            except (TypeError, ValueError, AttributeError):
                pass

    def _eject_locked(self, rep: Replica, reason: str) -> None:
        if rep.up:
            rep.up = False
            rep.ejections += 1
            log.warning("router: ejecting %s (%s)", rep.name, reason)

    # ---------------------------------------------------------------- routing
    def route_key(self, prompt, model: str | None = None) -> bytes | None:
        """The affinity key: a digest of ``(model, template)`` — the
        prompt's leading ``prefill_chunk``-aligned blocks, exactly the
        granularity the prefix cache stores (PR 2), NAMESPACED by the
        request's model. Two models sharing a prompt template must not
        collide on one rendezvous bucket: each engine owns its own
        prefix pool, so the cache working sets are disjoint and
        co-locating them would double one replica's trie pressure while
        its rendezvous peers idle. ``model=None`` (single-model fleets)
        keeps the pure-template digest. None when affinity is off or
        the prompt has no full block (nothing cacheable to be sticky
        about)."""
        n = (len(prompt) // self.prefill_chunk) * self.prefill_chunk
        if not self.affinity or n <= 0:
            return None
        body = ",".join(str(int(t)) for t in prompt[:n]).encode()
        if model is not None:
            body = f"{model}|".encode() + body
        return hashlib.sha1(body).digest()

    def _ranked_locked(self, key: bytes | None,
                       model: str | None = None,
                       exclude: set | None = None) -> list[Replica]:
        # prefill-role replicas never serve a complete request (their
        # /generate terminal is "prefilled" + a handoff payload, zero
        # tokens) — the classic single-leg path must not land on one.
        # They are reachable ONLY through the disaggregated two-leg
        # path (_try_disagg), which picks them explicitly.
        live = [r for r in self.replicas.values()
                if r.up and r.role != "prefill"]
        if model is not None:
            # model-aware routing dimension: route/spill only among
            # replicas advertising the request's model (empty set =
            # legacy replica, serves any). Affinity and least-loaded
            # both rank WITHIN the advertising subset, so spill never
            # lands a model on weights that can't serve it. ``exclude``
            # drops replicas that already answered 400 for this
            # request's model (a not-yet-polled advertisement window).
            live = [r for r in live
                    if (not r.models or model in r.models)
                    and (not exclude or r.name not in exclude)]
        if key is None:
            # least-loaded from the freshest /stats; name tie-break so
            # equal-load picks are deterministic
            return sorted(live, key=lambda r: (r.load, r.name))
        return sorted(
            live,
            key=lambda r: hashlib.sha1(key + r.name.encode()).digest(),
            reverse=True)

    def _saturated_locked(self, rep: Replica, now: float) -> bool:
        if rep.saturated_until > now:
            return True
        if rep.max_queue and rep.queued >= rep.max_queue:
            return True
        return (self.spill_queue_depth is not None
                and max(rep.queued, rep.inflight - max(0, rep.slots))
                >= self.spill_queue_depth)

    def _pick(self, key: bytes | None, model: str | None = None,
              exclude: set | None = None) -> Replica | None:
        """Choose a replica: rendezvous-sticky (or least-loaded) with
        spill past saturated candidates; when everything is saturated,
        the first choice anyway — the caller handles its 429."""
        now = time.monotonic()
        with self._lock:
            ranked = self._ranked_locked(key, model, exclude)
            if not ranked:
                return None
            for rep in ranked:
                if not self._saturated_locked(rep, now):
                    return rep
            return ranked[0]

    def _pick_prefill(self, model: str | None = None) -> Replica | None:
        """Least-loaded live prefill-specialist replica (they are
        compute-bound and phase-uniform, so load beats rendezvous
        stickiness here — the DECODE leg keeps the template's trie
        affinity). Saturated specialists are skipped while any other is
        available; None when the fleet has no live prefill replica (the
        caller uses the classic single-leg path)."""
        now = time.monotonic()
        with self._lock:
            live = [r for r in self.replicas.values()
                    if r.up and r.role == "prefill"
                    and (model is None or not r.models
                         or model in r.models)]
            if not live:
                return None
            avail = [r for r in live
                     if not self._saturated_locked(r, now)]
            return min(avail or live, key=lambda r: (r.load, r.name))

    def _post_import(self, rep: Replica, handoff: dict, timeout: float,
                     on_frame=None,
                     extra_headers: dict | None = None) -> dict:
        """POST /kv/import to one decode-capable replica: the body is
        the prefill leg's handoff payload VERBATIM (the pinned transfer
        contract); stream selection rides the query string, and
        ``extra_headers`` (the X-Tony-Trace stamp) ride the POST — the
        trace context can't ride the pinned body. Same error taxonomy
        as _post_generate — a 400 here means the payload was damaged in
        flight (torn transfer), which the caller maps onto the replay
        fallback."""
        url = rep.base_url + "/kv/import"
        if on_frame is not None:
            url += "?stream=true"
        body = json.dumps(handoff).encode()
        headers = {"Content-Type": "application/json"}
        if extra_headers:
            headers.update(extra_headers)
        req = urllib.request.Request(url, data=body, headers=headers)
        try:
            with urllib.request.urlopen(
                    req, timeout=max(0.05, timeout)) as resp:
                if on_frame is None:
                    return json.loads(resp.read().decode())
                return self._read_stream(rep, resp, on_frame,
                                         time.monotonic()
                                         + max(0.05, timeout))
        except urllib.error.HTTPError as e:
            if e.code == 429:
                try:
                    ra = int(e.headers.get("Retry-After", "1") or "1")
                except ValueError:
                    ra = 1
                raise _ReplicaShed(ra) from None
            if 400 <= e.code < 500:
                try:
                    detail = json.loads(e.read().decode()).get("error", "")
                except Exception:
                    detail = ""
                raise _ReplicaClientError(
                    f"HTTP {e.code} from {rep.name}"
                    + (f": {detail}" if detail else "")) from None
            raise _ReplicaUnavailable(f"HTTP {e.code}") from None
        except (StreamConsumerError, _ReplicaUnavailable,
                _ReplicaTimeout):
            raise
        except Exception as e:
            reason = getattr(e, "reason", None)
            if isinstance(e, TimeoutError) or isinstance(reason,
                                                         TimeoutError):
                raise _ReplicaTimeout(f"{type(e).__name__}: {e}") \
                    from None
            refused = isinstance(e, ConnectionRefusedError) or \
                isinstance(reason, ConnectionRefusedError)
            raise _ReplicaUnavailable(
                f"{type(e).__name__}: {e}", never_sent=refused) from None

    def _try_disagg(self, rid: int, tr, key, payload: dict,
                    deadline: float, model, on_frame,
                    collected: list) -> dict | None:
        """The disaggregated two-leg path (docs/serving.md
        'Disaggregated serving'): prefill on a least-loaded prefill
        specialist, then hand the exported KV blocks to the rendezvous
        decode replica via POST /kv/import and return (or relay) ITS
        completion. Returns None on any leg failure — the caller falls
        back to the classic single-leg path, which re-prefills from the
        prompt on a decode-capable replica (the journal-replay recovery
        shape: a dead prefill replica, a torn payload, or a full decode
        pool each cost recompute, never the request)."""
        pre = self._pick_prefill(model)
        if pre is None:
            return None
        with self._lock:
            self.disagg_requests += 1
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return None

        def _fallback(msg: str) -> None:
            with self._lock:
                self.disagg_fallbacks += 1
            log.debug("router: disagg fallback for request %d: %s",
                      rid, msg)

        # every leg carries this router's trace stamp: the replicas'
        # own spans join the request's distributed trace
        ctx = tr.ctx
        hdr = {TRACE_HEADER: ctx.to_header()} if ctx is not None else None
        # ---- leg 1: prefill (buffered — the handoff payload rides the
        # /generate response; streaming starts on the decode leg)
        leg1 = dict(payload)
        leg1.pop("stream", None)
        leg1["timeout_s"] = max(0.05, remaining)
        tr.attrs["prefill_replica"] = pre.name
        with self._lock:
            pre.requests += 1
            pre.inflight += 1
        t_leg1 = time.monotonic()
        try:
            resp1 = self._post_generate(pre, leg1, remaining,
                                        extra_headers=hdr)
        except _ReplicaShed as e:
            with self._lock:
                pre.shed += 1
                pre.retry_after_s = e.retry_after_s
                pre.saturated_until = (time.monotonic()
                                       + min(e.retry_after_s, 30))
            _fallback(f"{pre.name} shed the prefill leg")
            return None
        except _ReplicaUnavailable as e:
            with self._lock:
                pre.errors += 1
                self._eject_locked(pre, f"disagg prefill leg: {e}")
            _fallback(f"{pre.name} unavailable: {e}")
            return None
        except (_ReplicaTimeout, _ReplicaClientError) as e:
            # timeout: the outer loop's deadline check decides; client
            # error: the classic path will surface the same 400 — the
            # fallback keeps ONE error-reporting surface
            _fallback(f"{pre.name}: {e}")
            return None
        finally:
            with self._lock:
                pre.inflight -= 1
        if resp1.get("finish_reason") != "prefilled":
            # stale role advertisement: the replica served the whole
            # request — deliver what we already paid for
            if on_frame is not None and resp1.get("tokens"):
                on_frame(resp1["tokens"])
            resp1["replica"] = pre.name
            resp1.setdefault("retries", 0)
            return resp1
        leg_prefill = time.monotonic() - t_leg1
        with self._lock:
            self.leg_hists["prefill"].observe(leg_prefill)
        tr.mark("prefill_done")
        tr.attrs["leg_prefill_s"] = round(leg_prefill, 6)
        handoff = resp1.get("handoff")
        if not handoff:
            _fallback(f"{pre.name} prefilled but the export stash "
                      "aged out")
            return None

        # ---- leg 2: import + decode on the rendezvous replica (the
        # decode-side trie adopts the imported prefix blocks, so
        # template affinity keeps paying on the decode tier)
        dec = self._pick(key, model)
        if dec is None:
            _fallback("no live decode-capable replica")
            return None
        with self._lock:
            dec.requests += 1
            dec.inflight += 1
        # leg-2 attribution: submit -> first relayed frame is the
        # TRANSFER (payload ship + block install), the rest is DECODE.
        # A buffered leg 2 has no frame instants — it books entirely as
        # decode (documented on router_leg_seconds).
        t_leg2 = time.monotonic()
        first_frame_t = [None]
        leg2_frame = on_frame
        if on_frame is not None:
            def leg2_frame(delta, _inner=on_frame):
                if first_frame_t[0] is None:
                    first_frame_t[0] = time.monotonic()
                _inner(delta)
        try:
            resp2 = self._post_import(
                dec, handoff, deadline - time.monotonic(),
                on_frame=leg2_frame, extra_headers=hdr)
        except _ReplicaShed as e:
            with self._lock:
                dec.shed += 1
                dec.retry_after_s = e.retry_after_s
                dec.saturated_until = (time.monotonic()
                                       + min(e.retry_after_s, 30))
            _fallback(f"{dec.name} shed the import leg")
            return None
        except _ReplicaUnavailable as e:
            with self._lock:
                dec.errors += 1
                self._eject_locked(dec, f"disagg import leg: {e}")
            # a partially-relayed decode stream is a true prefix: carry
            # it so the fallback resumes instead of re-decoding
            if collected:
                payload["resume_tokens"] = list(collected)
            _fallback(f"{dec.name} unavailable: {e}")
            return None
        except (_ReplicaTimeout, _ReplicaClientError) as e:
            # client error = damaged/torn payload rejected LOUDLY by
            # import_blocks: exactly the case the replay fallback is
            # for (re-prefill from the prompt)
            _fallback(f"{dec.name}: {e}")
            return None
        finally:
            with self._lock:
                dec.inflight -= 1
        t_end = time.monotonic()
        split = first_frame_t[0] if first_frame_t[0] is not None else t_leg2
        leg_transfer = split - t_leg2
        leg_decode = t_end - split
        tr.attrs["leg_transfer_s"] = round(leg_transfer, 6)
        tr.attrs["leg_decode_s"] = round(leg_decode, 6)
        with self._lock:
            if first_frame_t[0] is not None:
                self.leg_hists["transfer"].observe(leg_transfer)
            self.leg_hists["decode"].observe(leg_decode)
            self.disagg_handoffs += 1
            if key is not None:
                ranked = self._ranked_locked(key, model)
                if ranked and ranked[0] is dec:
                    self.affinity_hits += 1
        if on_frame is not None:
            resp2.setdefault("tokens", list(collected))
        resp2["replica"] = dec.name
        resp2["prefill_replica"] = pre.name
        resp2.setdefault("retries", 0)
        tr.attrs.update(disagg=True, replica=dec.name)
        return resp2

    def fleet_model_fallback(self) -> str:
        """The /v1 ``model`` echo for requests that name none. The
        serve front door echoes its first-registered model
        (``app.default_model``); the router can't know registration
        order, but a fleet whose replicas advertise exactly ONE model
        name (the common single-model case) has an unambiguous answer.
        Multi-model or not-yet-polled fleets echo "default"."""
        with self._lock:
            names: set[str] = set()
            for rep in self.replicas.values():
                names |= rep.models
        return names.pop() if len(names) == 1 else "default"

    # ------------------------------------------------------------- the request
    def generate(self, prompt, max_new_tokens: int = 64,
                 timeout_s: float = 600.0, temperature: float | None = None,
                 top_k: int | None = None,
                 cache_prompt: bool | None = None,
                 model: str | None = None,
                 on_tokens=None, stop: list | None = None,
                 logprobs: int = 0,
                 priority: str | None = None,
                 last_event_id: str | None = None,
                 request_id: str | None = None,
                 trace=None) -> dict:
        """Route one generation request; returns the replica's response
        dict (id/tokens/finish_reason) plus routing attrs. ``model``
        restricts routing to replicas advertising that model (their
        /stats registry). Raises NoReplicaError / FleetSaturatedError /
        RouterError / TimeoutError — never returns a half-answer.

        ``on_tokens`` turns the request into a STREAMING pass-through:
        the replica is asked with ``stream=true``, every relayed token
        delta is handed to ``on_tokens(list_of_ints)`` exactly once
        (failover re-sends of the resume prefix are deduped by absolute
        position), and the emitted-so-far prefix is HARVESTED from the
        stream itself as the failover resume state — fresher than any
        /progress poll, which stays the fallback for non-streamed
        requests. The returned dict still carries the FULL token list.
        An ``on_tokens`` failure (the front-door client vanished)
        raises StreamConsumerError: no retry, no ejection.

        ``priority`` ("interactive" | "batch") passes through to the
        replica's admission tiers; ``last_event_id`` forwards a
        reconnecting client's ``Last-Event-ID`` header to the FIRST
        replica attempt (best effort — the replica that parked the
        prefix resumes it, any other starts fresh; retries fall back
        to the router's own /progress-harvested resume).

        ``request_id`` (client-supplied, optional) makes the request's
        progress key PORTABLE across shared-nothing routers
        (``req:<id>``): if a router dies mid-request, the front-door
        retry through ANY surviving router — same id — harvests the
        prefix the dead router's attempt journaled on the owning
        replica and carries it as ``resume_tokens``, so a router death
        costs recompute of the gap, never the request (docs/serving.md
        "Router tier HA").

        ``trace`` (an observability.TraceContext, or its dict form)
        places this relay in a distributed trace; None mints one —
        derived from ``request_id`` when given, so a cross-door retry
        of the same client request lands in the SAME trace_id without
        the doors ever exchanging a byte (docs/observability.md
        "Distributed tracing")."""
        with self._lock:
            self._relay_inflight += 1
            if on_tokens is not None:
                self.streams_active += 1
        try:
            return self._generate(prompt, max_new_tokens, timeout_s,
                                  temperature, top_k, cache_prompt,
                                  model, on_tokens, stop, logprobs,
                                  priority, last_event_id, request_id,
                                  trace)
        finally:
            with self._lock:
                self._relay_inflight -= 1
                if on_tokens is not None:
                    self.streams_active -= 1

    def _generate(self, prompt, max_new_tokens, timeout_s, temperature,
                  top_k, cache_prompt, model, on_tokens,
                  stop=None, logprobs=0, priority=None,
                  last_event_id=None, request_id=None,
                  trace=None) -> dict:
        rid = next(self._ids)
        tr = RequestTrace(rid)
        tr.mark("submitted")
        ctx = trace if isinstance(trace, TraceContext) \
            else TraceContext.from_dict(trace)
        if ctx is None:
            # root of the distributed trace. A client request_id
            # DERIVES the trace_id: a failover re-POST of the same id
            # through another shared-nothing door lands in the same
            # trace with zero coordination (the tracing analogue of
            # the portable req:<id> progress key)
            ctx = (TraceContext.for_request_id(str(request_id))
                   if request_id is not None else TraceContext.mint())
        tr.bind(ctx)
        tr.attrs["service"] = "router"
        tr.attrs["router"] = self._nonce
        key = self.route_key(prompt, model)
        with self._lock:
            self.requests_total += 1
            if key is not None:
                self.affinity_requests += 1
            if request_id is not None:
                # portable progress key: every router derives the SAME
                # key from the client's id, so the journaled prefix is
                # readable across the shared-nothing tier
                self._pkeys[rid] = f"req:{request_id}"
                tr.attrs["request_id"] = str(request_id)
        deadline = time.monotonic() + timeout_s
        payload = {"prompt": [int(t) for t in prompt],
                   "max_new_tokens": int(max_new_tokens),
                   # failover-resume handle: the health loop polls the
                   # serving replica's /progress under this key so a
                   # mid-request death resumes elsewhere from the last
                   # journaled prefix instead of from scratch
                   "progress_key": self._pkey(rid)}
        if request_id is not None and last_event_id is None:
            # cross-router resume: a retry of a request a DEAD router
            # had in flight. Shared-nothing agreement makes the owning
            # replica discoverable without coordination — this router's
            # rendezvous pick over the same replica NAMES is the same
            # replica the dead router posted to — so ask its /progress
            # for the portable key once, before routing. An empty
            # answer (fresh request, or the journal already sealed)
            # costs one sub-probe-timeout poll and nothing else.
            owner = self._pick(key, model)
            if owner is not None:
                prior = (self._fetch_progress(
                    owner, [self._pkey(rid)],
                    timeout=min(0.5, self.probe_timeout_s))
                    .get(self._pkey(rid)) or {}).get("tokens")
                if prior:
                    payload["resume_tokens"] = [int(t) for t in prior]
                    with self._lock:
                        self.resumed_tokens_total += len(prior)
                        self._resume[rid] = [int(t) for t in prior]
                    tr.attrs["resumed_tokens"] = len(prior)
                    tr.attrs["cross_router_resume"] = True
        if on_tokens is not None:
            payload["stream"] = True
        # streaming relay state: `collected` is the CURRENT attempt's
        # absolute stream (each attempt re-sends the resume prefix from
        # position 0), `forwarded` the tokens already handed to the
        # consumer across every attempt — the dedupe that makes a
        # failover invisible to the client
        collected: list[int] = []
        forwarded = 0

        def on_frame(delta):
            nonlocal forwarded
            collected.extend(int(t) for t in delta)
            if len(collected) > forwarded:
                new = collected[forwarded:]
                forwarded = len(collected)
                with self._lock:
                    self.streamed_tokens_total += len(new)
                try:
                    on_tokens(new)
                except Exception as e:
                    raise StreamConsumerError(
                        f"stream consumer failed: {type(e).__name__}: "
                        f"{e}") from e
        if temperature is not None:
            payload["temperature"] = float(temperature)
        if top_k is not None:
            payload["top_k"] = int(top_k)
        if cache_prompt is not None:
            payload["cache_prompt"] = bool(cache_prompt)
        if stop is not None:
            # pass-through: the replica engine validates/normalizes
            payload["stop"] = stop
        if logprobs:
            payload["logprobs"] = int(logprobs)
        if model is not None:
            payload["model"] = str(model)
            tr.attrs["model"] = str(model)
        if priority is not None:
            # pass-through: the replica validates the tier name
            payload["priority"] = str(priority)
            tr.attrs["priority"] = str(priority)
        # write-ahead OPEN record: a SIGKILLed door seals nothing, so
        # this door's relay span would otherwise vanish from the merged
        # trace. Identifiable by its non-terminal last span; the sealed
        # record supersedes it at merge time (the TraceCollector fence
        # keeps the richer record for the same span_id).
        sink = self.trace_sink
        if sink is not None:
            try:
                sink(tr.to_dict())
            except Exception:
                log.exception("router trace sink failed (open record)")
        # disaggregated two-leg attempt first (only when the fleet has
        # live prefill specialists; a roleless/mixed fleet skips this
        # entirely). SSE reconnects stay on the classic path — the
        # parked prefix lives on one specific replica — and so do
        # cross-router resumes: the harvested prefix replays through
        # the classic teacher-forcing path, not a prefill handoff.
        if last_event_id is None and "resume_tokens" not in payload:
            resp = self._try_disagg(
                rid, tr, key, payload, deadline, model,
                on_frame if on_tokens is not None else None, collected)
            if resp is not None:
                self._seal(tr, "finished", retries=0,
                           n_tokens=len(resp.get("tokens", [])))
                return resp
        attempts = 0
        min_retry_after: int | None = None
        failover_pending = False    # a failover counts when it POSTS
        # replicas that answered 400 for THIS request's model (their
        # advertisement hadn't been polled yet): excluded from
        # re-picks, never retried — but the request itself re-routes
        wrong_model: set[str] = set()
        last_err = "no replica available"
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._seal(tr, "failed", error="deadline", retries=attempts)
                raise TimeoutError(
                    f"request {rid} exhausted its {timeout_s}s budget after "
                    f"{attempts} attempts (last: {last_err})")
            t0 = time.monotonic()
            rep = self._pick(key, model, wrong_model or None)
            dt = time.monotonic() - t0
            with self._lock:    # Histogram is not thread-safe
                self.routing_hist.observe(dt)
            if rep is None:
                if model is not None:
                    # fail FAST when the fleet is live and fully
                    # model-aware but nobody advertises the name: the
                    # router already knows the answer, and spinning out
                    # the client deadline in re-pick beats would pin a
                    # handler thread per typo'd model. (Replicas whose
                    # advertisement hasn't been polled yet have empty
                    # sets and route as serve-anything, so a fresh
                    # router never hits this branch spuriously.)
                    with self._lock:
                        live = [r for r in self.replicas.values()
                                if r.up]
                        fleet_knows = bool(live) and all(
                            r.models for r in live)
                    if fleet_knows:
                        self._seal(tr, "failed", error="no_replica",
                                   retries=attempts)
                        raise NoReplicaError(
                            f"no live replica advertises model "
                            f"{model!r} (check the fleet's --model "
                            "registrations)")
                # nothing live: give health/discovery a beat to find one
                last_err = ("no live replica" if model is None else
                            f"no live replica advertises model {model!r}")
                if self._sleep(min(0.25, remaining), deadline):
                    continue        # still time: re-pick
                self._seal(tr, "failed", error="no_replica",
                           retries=attempts)
                if model is not None:
                    raise NoReplicaError(
                        f"no live replica advertises model {model!r} "
                        "(check the fleet's --model registrations)")
                raise NoReplicaError(
                    "no live replica in the fleet (all ejected or none "
                    "discovered)")
            with self._lock:
                rep.requests += 1
                rep.inflight += 1
                if attempts:
                    rep.retries += 1
                self._outstanding[rid] = rep.name
                if failover_pending:
                    # the resubmission is actually happening: THIS is
                    # the failover (counting in the error handler would
                    # overcount requests that then die on the deadline
                    # without ever re-posting)
                    failover_pending = False
                    self.failovers_total += 1
                    self.resumed_tokens_total += len(
                        payload.get("resume_tokens", ()))
                    if on_tokens is not None:
                        # a STREAM resumed mid-relay: the client keeps
                        # reading one uninterrupted stream while the
                        # request moves replicas underneath it
                        self.stream_failovers_total += 1
            tr.mark("routed")
            tr.attrs.update(replica=rep.name, attempt=attempts + 1)
            # the replica enforces the same deadline: a request the
            # router would abandon must not keep decoding downstream
            payload["timeout_s"] = max(0.05, remaining)
            collected.clear()       # each attempt streams from position 0
            # every attempt — first post, failover resubmits with
            # resume_tokens alike — carries this router's trace stamp
            hdrs = {TRACE_HEADER: ctx.to_header()}
            if last_event_id and attempts == 0:
                # SSE reconnect pass-through: only the FIRST attempt
                # forwards the client's header — a failover retry
                # resumes via the router's own harvested resume_tokens
                # instead, and sending both would double-resume
                hdrs["Last-Event-ID"] = last_event_id
            t_leg = time.monotonic()
            try:
                try:
                    resp = self._post_generate(
                        rep, payload, remaining,
                        on_frame=(on_frame if on_tokens is not None
                                  else None),
                        extra_headers=hdrs)
                finally:
                    with self._lock:
                        rep.inflight -= 1
            except _ReplicaShed as e:
                attempts += 1
                now = time.monotonic()
                with self._lock:
                    rep.shed += 1
                    rep.retry_after_s = e.retry_after_s
                    # backpressure window, capped: Retry-After is an ETA
                    # for ONE seat, not a ban — re-probe within a beat
                    rep.saturated_until = now + min(e.retry_after_s, 30)
                    all_saturated = all(
                        self._saturated_locked(r, now)
                        for r in self.replicas.values() if r.up)
                min_retry_after = (e.retry_after_s if min_retry_after is None
                                   else min(min_retry_after, e.retry_after_s))
                last_err = f"{rep.name} shed (Retry-After {e.retry_after_s}s)"
                if not all_saturated:
                    continue        # spill immediately to the next choice
                # the whole fleet is backpressuring: honor the smallest
                # advertised Retry-After (jittered so synchronized callers
                # don't stampede back in one wave), or give up if the
                # deadline lands first
                wait = min_retry_after * self._rng.uniform(0.5, 1.0)
                if time.monotonic() + wait >= deadline:
                    with self._lock:
                        self.shed_total += 1
                    self._seal(tr, "shed", retries=attempts,
                               retry_after_s=min_retry_after)
                    raise FleetSaturatedError(
                        f"every live replica is shedding (request {rid}, "
                        f"{attempts} attempts)", min_retry_after)
                self._sleep(wait, deadline)
            except _ReplicaTimeout as e:
                # the CALLER's deadline expired mid-generation: fail this
                # attempt only — ejection is for replica faults, and the
                # health loop will catch a genuinely dead server
                attempts += 1
                with self._lock:
                    rep.errors += 1
                last_err = f"{rep.name} timed out: {e}"
                continue        # top-of-loop deadline check ends it
            except _ReplicaUnavailable as e:
                attempts += 1
                with self._lock:
                    rep.errors += 1
                    self._eject_locked(rep, str(e))
                # replay-aware failover: re-ask the failed replica for
                # the freshest emitted prefix (a 5xx replica is usually
                # still alive; a SIGKILLed one refuses in microseconds
                # and the health loop's last poll stands), then carry
                # the best-known prefix on the resubmission so the next
                # replica resumes instead of restarting from scratch.
                # A REFUSED connection means the request never reached
                # the replica: plain re-route, nothing in flight there
                # to ask about, and not a failover for the counter.
                if not e.never_sent:
                    # harvest the relayed STREAM's prefix first — it is
                    # at least as fresh as any poll, and doing it here
                    # (once, at failover) instead of per frame keeps
                    # the hot relay path free of O(stream) list copies
                    # under the router lock
                    with self._lock:
                        if len(collected) > len(
                                self._resume.get(rid, ())):
                            self._resume[rid] = list(collected)
                    pkey = self._pkey(rid)
                    fresh = (self._fetch_progress(
                        rep, [pkey],
                        timeout=min(0.5, self.probe_timeout_s))
                        .get(pkey) or {}).get("tokens")
                    with self._lock:
                        if fresh and len(fresh) > len(
                                self._resume.get(rid, ())):
                            self._resume[rid] = [int(t) for t in fresh]
                        known = list(self._resume.get(rid, ()))
                    failover_pending = True
                    if known:
                        payload["resume_tokens"] = known
                        tr.attrs["resumed_tokens"] = len(known)
                last_err = f"{rep.name}: {e}"
                # jittered exponential backoff before re-ranking — the
                # survivors absorb the traffic; the health loop readmits
                # the ejected replica when it comes back
                backoff = (min(0.05 * (2 ** min(attempts, 6)), 2.0)
                           * self._rng.uniform(0.5, 1.5))
                self._sleep(min(backoff, max(0.0, deadline
                                             - time.monotonic())), deadline)
            except StreamConsumerError:
                # the front-door CLIENT vanished mid-relay: not a
                # replica fault — closing the downstream connection
                # already triggered the replica's own disconnect
                # cancel; no retry, no ejection
                with self._lock:
                    self.stream_disconnects_total += 1
                self._seal(tr, "failed", error="client_gone",
                           retries=attempts)
                raise
            except _ReplicaClientError as e:
                if model is not None and (
                        not rep.models or model not in rep.models):
                    # a MIS-ROUTE, not a bad request: the replica's
                    # advertisement hadn't been polled yet (empty set
                    # routes as serve-anything) and it doesn't serve
                    # this model — exclude it for this request and
                    # re-pick; a live advertiser elsewhere still gets
                    # the request
                    attempts += 1
                    wrong_model.add(rep.name)
                    last_err = f"{rep.name}: {e}"
                    continue
                # the replica says the REQUEST itself is malformed: no
                # retry — another replica would say the same — and no
                # ejection
                self._seal(tr, "failed", error="client", retries=attempts)
                raise RouterClientError(str(e)) from None
            else:
                leg_relay = time.monotonic() - t_leg
                tr.attrs["leg_relay_s"] = round(leg_relay, 6)
                with self._lock:
                    self.leg_hists["relay"].observe(leg_relay)
                    ranked = (self._ranked_locked(key, model)
                              if key is not None else [])
                    hit = bool(ranked and ranked[0] is rep)
                    if hit:
                        self.affinity_hits += 1
                if on_tokens is not None:
                    # the streaming final frame carries no token list;
                    # the relayed stream IS the result — return it so
                    # the caller's shape matches the buffered path
                    resp.setdefault("tokens", list(collected))
                self._seal(tr, "finished", retries=attempts,
                           affinity_hit=bool(hit),
                           n_tokens=len(resp.get("tokens", [])))
                resp["replica"] = rep.name
                resp["retries"] = attempts
                return resp

    def _sleep(self, seconds: float, deadline: float) -> bool:
        """Bounded wait; True if the deadline survived it."""
        if seconds > 0:
            time.sleep(seconds)
        return time.monotonic() < deadline

    def _post_generate(self, rep: Replica, payload: dict,
                       timeout: float, on_frame=None,
                       extra_headers: dict | None = None) -> dict:
        """POST /generate to one replica. ``on_frame`` switches to the
        SSE relay: each token-delta frame is handed to it as it
        arrives, and the replica's closing frame is returned in place
        of the buffered response. A replica answering a stream request
        with a buffered body (predates streaming) degrades gracefully:
        its full token list is delivered as one frame.
        ``extra_headers`` ride the POST verbatim (the Last-Event-ID
        reconnect pass-through)."""
        body = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        if extra_headers:
            headers.update(extra_headers)
        req = urllib.request.Request(
            rep.base_url + "/generate", data=body, headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=max(0.05,
                                                         timeout)) as resp:
                if on_frame is None:
                    return json.loads(resp.read().decode())
                return self._read_stream(rep, resp, on_frame,
                                         time.monotonic()
                                         + max(0.05, timeout))
        except urllib.error.HTTPError as e:
            if e.code == 429:
                try:
                    ra = int(e.headers.get("Retry-After", "1") or "1")
                except ValueError:
                    ra = 1
                raise _ReplicaShed(ra) from None
            if 400 <= e.code < 500:
                # the request is malformed (unknown model, bad params):
                # the replica is healthy and a retry would repeat it
                try:
                    detail = json.loads(e.read().decode()).get("error", "")
                except Exception:
                    detail = ""
                raise _ReplicaClientError(
                    f"HTTP {e.code} from {rep.name}"
                    + (f": {detail}" if detail else "")) from None
            raise _ReplicaUnavailable(f"HTTP {e.code}") from None
        except (StreamConsumerError, _ReplicaUnavailable,
                _ReplicaTimeout):
            raise               # _read_stream already classified these
        except Exception as e:      # URLError, socket timeout, reset, ...
            reason = getattr(e, "reason", None)
            if isinstance(e, TimeoutError) or isinstance(reason,
                                                         TimeoutError):
                raise _ReplicaTimeout(f"{type(e).__name__}: {e}") \
                    from None
            refused = isinstance(e, ConnectionRefusedError) or \
                isinstance(reason, ConnectionRefusedError)
            raise _ReplicaUnavailable(
                f"{type(e).__name__}: {e}", never_sent=refused) from None

    def _read_stream(self, rep: Replica, resp, on_frame,
                     deadline: float) -> dict:
        """Relay one replica's SSE response: token-delta frames go to
        ``on_frame`` as they arrive; returns the closing frame (the
        one carrying ``finish_reason``). Raises _ReplicaUnavailable on
        a severed/errored stream (the failover trigger — the harvested
        prefix is already in ``_resume``), _ReplicaTimeout past the
        caller's deadline, StreamConsumerError untouched."""
        ctype = resp.headers.get("Content-Type", "")
        if not ctype.startswith("text/event-stream"):
            # pre-streaming replica: buffered body, delivered as one
            # frame so the consumer contract holds
            data = json.loads(resp.read().decode())
            if data.get("tokens"):
                on_frame(data["tokens"])
            return data
        final = None
        try:
            for raw in resp:
                line = raw.strip()
                if not line.startswith(b"data: "):
                    continue
                if time.monotonic() >= deadline:
                    raise _ReplicaTimeout("stream outlived the deadline")
                text = line[6:].decode()
                if text == "[DONE]":
                    break
                obj = json.loads(text)
                if "error" in obj:
                    # in-band failure (loop crash mid-stream): same
                    # taxonomy as a 5xx — retry/failover elsewhere
                    raise _ReplicaUnavailable(
                        f"in-stream error: {obj['error']}")
                if obj.get("finish_reason") is not None:
                    final = obj
                    break
                toks = obj.get("tokens")
                if toks:
                    on_frame(toks)
        except (StreamConsumerError, _ReplicaUnavailable,
                _ReplicaTimeout):
            raise
        except Exception as e:
            # severed mid-stream (SIGKILL, reset, short read): the
            # mid-request failover trigger
            raise _ReplicaUnavailable(
                f"stream severed: {type(e).__name__}: {e}") from None
        if final is None:
            raise _ReplicaUnavailable(
                "stream ended without a terminal frame")
        return final

    def _seal(self, tr: RequestTrace, terminal: str, **attrs) -> None:
        tr.attrs.update(attrs)
        tr.mark(terminal)
        e2e = tr.spans[-1][1] - tr.spans[0][1]
        with self._lock:
            self.e2e_hist.observe(max(0.0, e2e))
            if terminal == "failed":
                self.failed_total += 1
            # terminal: stop progress-polling this request and drop its
            # journaled prefix
            self._outstanding.pop(tr.id, None)
            self._resume.pop(tr.id, None)
            self._pkeys.pop(tr.id, None)
        sink = self.trace_sink
        if sink is not None:
            try:
                sink(tr.to_dict())
            except Exception:
                log.exception("router trace sink failed")

    # ---------------------------------------------------------- observability
    def stats(self) -> dict:
        with self._lock:
            reps = {
                r.name: {
                    "endpoint": f"{r.host}:{r.port}", "up": r.up,
                    "queued": r.queued, "active": r.active,
                    "inflight": r.inflight,
                    "slots": r.slots, "requests": r.requests,
                    "retries": r.retries, "shed": r.shed,
                    "errors": r.errors, "ejections": r.ejections,
                    # advertised model registry ([] = legacy replica:
                    # serves any model it's asked for)
                    "models": sorted(r.models),
                    # disaggregated-serving role advertisement
                    "role": r.role,
                    "ttft_p99_s": round(r.ttft_p99_s, 6),
                } for r in self.replicas.values()}
            # per-role load aggregates: the two-tier autoscaler's
            # router-side signals (queue depth scales the prefill tier,
            # latency scales the decode tier — docs/autoscaling.md)
            roles: dict = {}
            for r in self.replicas.values():
                agg = roles.setdefault(r.role, {
                    "live": 0, "inflight": 0, "queued": 0, "active": 0})
                agg["live"] += 1 if r.up else 0
                agg["inflight"] += r.inflight
                agg["queued"] += max(0, r.queued)
                agg["active"] += max(0, r.active)
            return {
                "replicas": reps,
                "live": sum(r.up for r in self.replicas.values()),
                # known replicas, live AND ejected — with `live`, the
                # fleet-level view of ejection/readmission churn
                "fleet_size": len(self.replicas),
                # requests currently relayed through THIS router
                # (buffered + streamed): the router-tier saturation
                # signal the autoscaler scrapes, and the drain gate
                "relay_inflight": self._relay_inflight,
                # True once a SIGTERM/scale-down drain began: new
                # requests are refused while in-flight relays finish
                "draining": self.draining,
                # controller-readable fleet aggregate (tony_tpu/
                # autoscale.py): the merged load signals a scaling loop
                # needs in one place — router-outstanding posts are
                # fresher than any replica poll, queued/active lag one
                # stats refresh, ttft_p99_s is the WORST replica's own
                # cumulative p99 (the controller's windowed signal
                # comes from /metrics bucket deltas; this is the
                # coarse at-a-glance mirror)
                "fleet": {
                    "inflight": sum(r.inflight
                                    for r in self.replicas.values()),
                    "queued": sum(max(0, r.queued)
                                  for r in self.replicas.values()),
                    "active": sum(max(0, r.active)
                                  for r in self.replicas.values()),
                    "ttft_p99_s": round(max(
                        (r.ttft_p99_s for r in self.replicas.values()),
                        default=0.0), 6),
                    "roles": roles,
                },
                # disaggregated serving: two-leg attempts, completed
                # handoffs, and single-leg fallbacks (either leg died/
                # tore — recompute, never a lost request)
                "disagg_requests": self.disagg_requests,
                "disagg_handoffs": self.disagg_handoffs,
                "disagg_fallbacks": self.disagg_fallbacks,
                # True while driver discovery is failing/distrusted and
                # the router serves its last-known fleet (control-plane
                # outage; docs/training-robustness.md)
                "discovery_stale": self.discovery_stale,
                "requests": self.requests_total,
                "failed": self.failed_total,
                "shed": self.shed_total,
                # replay-aware failover: mid-request resubmissions and
                # the emitted tokens they carried instead of re-decoding
                "failovers": self.failovers_total,
                "resumed_tokens": self.resumed_tokens_total,
                # streaming pass-through: live relayed streams, tokens
                # forwarded, mid-stream failovers (prefix harvested
                # from the stream), clients gone mid-relay
                "streams_active": self.streams_active,
                "streamed_tokens": self.streamed_tokens_total,
                "stream_failovers": self.stream_failovers_total,
                "stream_disconnects": self.stream_disconnects_total,
                "affinity": {
                    "enabled": self.affinity,
                    "requests": self.affinity_requests,
                    "hits": self.affinity_hits,
                    "hit_ratio": round(
                        self.affinity_hits / self.affinity_requests, 4)
                    if self.affinity_requests else None,
                },
                "routing_decision_s": self.routing_hist.snapshot(),
                "request_s": self.e2e_hist.snapshot(),
            }

    def prometheus_metrics(self) -> str:
        """GET /metrics: the router_* families (docs/observability.md
        "Router metrics")."""
        r = PromRenderer()
        with self._lock:
            reps = list(self.replicas.values())
            live = sum(rep.up for rep in reps)
            for rep in sorted(reps, key=lambda x: x.name):
                lab = {"replica": rep.name}
                r.gauge(_metrics.ROUTER_REPLICA_UP, 1 if rep.up else 0,
                        "1 while the replica is in rotation, 0 while "
                        "ejected", labels=lab)
                r.counter(_metrics.ROUTER_REQUESTS_TOTAL, rep.requests,
                          "generate attempts posted per replica",
                          labels=lab)
                r.counter(_metrics.ROUTER_RETRIES_TOTAL, rep.retries,
                          "posts that were re-attempts of a request",
                          labels=lab)
                r.counter(_metrics.ROUTER_SHED_TOTAL, rep.shed,
                          "429 answers received per replica", labels=lab)
                r.counter(_metrics.ROUTER_EJECTIONS_TOTAL, rep.ejections,
                          "times the replica was ejected from rotation",
                          labels=lab)
            r.gauge(_metrics.ROUTER_REPLICAS_LIVE, live,
                    "replicas currently in rotation")
            r.gauge(_metrics.ROUTER_FLEET_SIZE, len(reps),
                    "replicas this router knows about, live and "
                    "ejected alike (discovery's newest view)")
            r.gauge(_metrics.ROUTER_REPLICAS, live,
                    "replica count by rotation state: ejection/"
                    "readmission churn at the fleet level",
                    labels={"state": "live"})
            r.gauge(_metrics.ROUTER_REPLICAS, len(reps) - live,
                    "replica count by rotation state: ejection/"
                    "readmission churn at the fleet level",
                    labels={"state": "ejected"})
            r.gauge(_metrics.ROUTER_RELAY_INFLIGHT, self._relay_inflight,
                    "requests currently relayed through this router "
                    "(buffered + streamed) — the router-tier "
                    "saturation signal the autoscaler scrapes")
            r.gauge(_metrics.ROUTER_DISCOVERY_STALE,
                    1 if self.discovery_stale else 0,
                    "1 while driver discovery is failing/distrusted and "
                    "the router serves its last-known fleet (the "
                    "operator's control-plane-outage signal)")
            r.counter(_metrics.ROUTER_FAILED_TOTAL, self.failed_total,
                      "requests the router could not complete "
                      "(deadline / no replica)")
            r.counter(_metrics.ROUTER_FAILOVERS_TOTAL,
                      self.failovers_total,
                      "mid-request resubmissions to another replica "
                      "after a transport failure/5xx, carrying the "
                      "journaled emitted prefix (resume_tokens)")
            r.gauge(_metrics.ROUTER_STREAMS_ACTIVE, self.streams_active,
                    "SSE streams currently relayed through this router")
            r.counter(_metrics.ROUTER_STREAMED_TOKENS_TOTAL,
                      self.streamed_tokens_total,
                      "tokens forwarded through relayed streams "
                      "(failover prefix re-sends deduped)")
            r.counter(_metrics.ROUTER_STREAM_FAILOVERS_TOTAL,
                      self.stream_failovers_total,
                      "mid-STREAM failovers: the relay moved replicas "
                      "with the resume prefix harvested from the "
                      "stream itself, invisibly to the client")
            r.counter(_metrics.ROUTER_STREAM_DISCONNECTS_TOTAL,
                      self.stream_disconnects_total,
                      "front-door clients that vanished mid-relay (the "
                      "downstream request is cancelled, not failed "
                      "over)")
            r.counter(_metrics.ROUTER_AFFINITY_HITS_TOTAL,
                      self.affinity_hits,
                      "keyed requests served by their sticky replica")
            r.counter(_metrics.ROUTER_AFFINITY_REQUESTS_TOTAL,
                      self.affinity_requests,
                      "requests that carried a prefix-affinity key")
            if self.affinity_requests:
                r.gauge(_metrics.ROUTER_AFFINITY_HIT_RATIO,
                        self.affinity_hits / self.affinity_requests,
                        "affinity_hits / affinity_requests — how often "
                        "the sticky replica actually served (spills and "
                        "ejections lower it)")
            r.counter(_metrics.ROUTER_DISAGG_REQUESTS_TOTAL,
                      self.disagg_requests,
                      "requests the router attempted to split across a "
                      "prefill specialist and a decode replica")
            r.counter(_metrics.ROUTER_DISAGG_HANDOFFS_TOTAL,
                      self.disagg_handoffs,
                      "completed prefill->decode handoffs (the prefill "
                      "leg's KV blocks imported via /kv/import and "
                      "decode resumed on them)")
            r.counter(_metrics.ROUTER_DISAGG_FALLBACKS_TOTAL,
                      self.disagg_fallbacks,
                      "disaggregated attempts that fell back to the "
                      "classic single-replica path (re-prefill from "
                      "the prompt — correctness kept, recompute paid)")
            r.histogram(_metrics.ROUTER_ROUTING_SECONDS, self.routing_hist,
                        "routing-decision latency (pick only, no I/O)")
            r.histogram(_metrics.ROUTER_E2E_SECONDS, self.e2e_hist,
                        "request time through the router, submit to "
                        "terminal, retries included")
            for leg, hist in sorted(self.leg_hists.items()):
                r.histogram(
                    _metrics.ROUTER_LEG_SECONDS, hist,
                    "router-attributed fleet time per request leg: "
                    "relay = classic single-replica POST, prefill = "
                    "disagg leg 1, transfer = leg-2 submit to first "
                    "relayed frame, decode = the rest (buffered leg 2 "
                    "books entirely as decode)",
                    labels={"leg": leg})
        return r.render()

    def healthy(self) -> bool:
        with self._lock:
            return any(r.up for r in self.replicas.values())

    def health(self) -> dict:
        """The router's OWN ``GET /healthz`` payload — distinct from
        per-replica health (which this router probes): an upstream load
        balancer fronting N shared-nothing routers uses it to eject a
        dead/wedged ROUTER exactly as this router ejects a dead
        replica. Unhealthy (503) when no replica is in rotation — the
        router cannot complete a request — or when the maintenance
        (health/discovery) loop was started and has died/stopped: a
        router with no liveness view serves a stale fleet and must
        leave rotation. ``health_loop_alive`` is None until ``start()``
        (a statically-configured router that never started the loop is
        still routable)."""
        with self._lock:
            live = sum(r.up for r in self.replicas.values())
            total = len(self.replicas)
            draining = self.draining
        loop_alive = None
        if self._health_started:
            loop_alive = (self._health_thread is not None
                          and self._health_thread.is_alive()
                          and not self._stop.is_set())
        return {"healthy": (bool(live) and loop_alive is not False
                            and not draining),
                "live": live, "replicas": total,
                "health_loop_alive": loop_alive,
                # a draining router must leave the LB rotation NOW —
                # it refuses new requests while in-flight relays finish
                "draining": draining}


class DriverDiscovery:
    """The driver-backed fleet view: reads ``driver.json`` for the RPC
    endpoint, then serves ``get_task_infos`` filtered down to RUNNING
    tasks that published a ``serve_port`` (runtimes/serving.py publishes
    it only after the replica's first healthy /healthz). A replica mid-
    restart has no ports (the driver clears them at relaunch) and drops
    out of the result until its new attempt is serving again.

    On any failure the cached RPC client is dropped so the NEXT call
    re-resolves driver.json — a RECOVERED driver (control-plane
    recovery) rewrites it with a fresh endpoint and restores the
    journaled ports, so discovery heals without a replica bounce; the
    router's ``_discovery_tick`` keeps the last-known fleet serving in
    the meantime (``router_discovery_stale``).

    ``min_interval_s`` caches a successful result that long (jittered
    ±10% from OS entropy, so N shared-nothing routers spread their
    ``get_task_infos`` reads instead of hammering the driver in
    lockstep waves at health-poll cadence), and a FAILED call backs
    off exponentially (0.5s doubling to 10s, same jitter) — during a
    control-plane outage N routers re-probing the dead endpoint every
    tick would synchronize into a recovery stampede the instant the
    driver returns. Within the backoff window the cached failure
    re-raises, so the router's ``_discovery_tick`` keeps reporting
    stale instead of mistaking the cache for a live view.

    ``token_role`` names what ``token`` IS. "client" (the default): the
    ROOT job secret, from which the client-role key is derived here. A
    router launched AS A TASK (the ``router`` framework) never sees the
    root secret — its env carries the driver's already-derived
    executor-role key — so the route CLI passes
    ``token_role="executor"`` and the token is used verbatim
    (``get_task_infos`` is not ACL-restricted; an executor key reads
    the fleet view but still cannot sign client-privileged calls)."""

    def __init__(self, job_dir: str, role: str | None = None,
                 token: str = "", min_interval_s: float = 0.0,
                 token_role: str = "client"):
        from pathlib import Path

        self.token_role = token_role
        self.job_dir = Path(job_dir)
        self.role = role
        self.min_interval_s = float(min_interval_s)
        self._token = token
        self._rpc = None
        self._jitter = random.Random()      # per-process phase
        self._cached: list | None = None
        self._cached_err: Exception | None = None
        self._next_t = 0.0
        self._backoff = 0.0

    def _client(self):
        if self._rpc is None:
            from . import constants as c
            from .rpc import RpcClient
            from .rpc.protocol import derive_role_key

            info = json.loads(
                (self.job_dir / c.DRIVER_INFO_FILE).read_text())
            # an executor-role token arrives pre-derived; only the root
            # secret needs the client-key derivation
            key = (derive_role_key(self._token, "client")
                   if self.token_role == "client" else self._token)
            self._rpc = RpcClient(
                info["host"], info["port"],
                token=key if self._token else "",
                role=self.token_role if self._token else "",
                max_retries=2)
        return self._rpc

    def __call__(self) -> list[tuple[str, str, int]]:
        now = time.monotonic()
        if now < self._next_t:
            # inside the cache/backoff window: replay the last outcome
            # without touching the driver
            if self._cached_err is not None:
                raise RuntimeError(
                    f"discovery backing off after: {self._cached_err}")
            if self._cached is not None:
                return list(self._cached)
        try:
            infos = self._client().call("get_task_infos")
        except Exception as e:
            self.close()            # re-resolve driver.json next tick
            self._cached_err = e
            # capped below the router's own discovery grace: a
            # recovered driver must be re-noticed before an empty/stale
            # view would be honored
            self._backoff = min(
                max(self._backoff * 2, 0.5), 10.0)
            self._next_t = now + (self._backoff
                                  * self._jitter.uniform(0.9, 1.1))
            raise
        out = []
        for info in infos:
            if self.role is not None and info.get("name") != self.role:
                continue
            if info.get("status") != "RUNNING":
                continue
            serve = (info.get("ports") or {}).get("serve_port")
            if not serve:
                continue
            task_id = f"{info['name']}:{info['index']}"
            out.append((task_id, info.get("host") or "127.0.0.1",
                        int(serve)))
        self._cached, self._cached_err, self._backoff = out, None, 0.0
        self._next_t = now + (self.min_interval_s
                              * self._jitter.uniform(0.9, 1.1))
        return out

    def close(self) -> None:
        if self._rpc is not None:
            self._rpc.close()
            self._rpc = None


# ------------------------------------------------------------- HTTP front door

def make_handler(router: FleetRouter, codec=None):
    import os
    import re
    import signal
    from http.server import BaseHTTPRequestHandler

    from . import constants as c
    from .api.openai import TokenCodec
    from .api.stream import begin_sse, read_json_body, sse_frame

    if codec is None:
        codec = TokenCodec("ids")
    # /v1 response ids: monotonic per router process (a handler
    # instance is reused across keep-alive requests, so id(self)
    # would hand two completions the same id)
    oai_ids = itertools.count()
    # deterministic fault injection for the router-HA gate: SIGKILL
    # this router upon RECEIVING its Nth front-door generate request —
    # mid-POST from the client's view, so the front-door retry path is
    # what survives it. "N" fires on any router; "IDX#N" only on the
    # task whose TONY_TASK_INDEX is IDX (targets one member of a fleet
    # that shares its env).
    kill_at = 0
    spec = os.environ.get(c.TEST_ROUTER_SIGKILL_AT_REQUEST, "")
    if spec:
        idx, sep, n = spec.rpartition("#")
        if not sep or idx == os.environ.get(c.ENV_TASK_INDEX, ""):
            kill_at = int(n)
    req_seq = itertools.count(1)

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code: int, obj: dict,
                  headers: dict | None = None) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _begin_sse(self) -> None:
            begin_sse(self)

        def do_GET(self):
            if self.path == "/healthz":
                # the ROUTER's own health (FleetRouter.health) —
                # deliberately NOT router.stats(): probers hit this at
                # liveness cadence, and the full stats payload computes
                # histogram quantiles under the routing lock
                payload = router.health()
                self._send(200 if payload["healthy"] else 503, payload)
            elif self.path == "/stats":
                self._send(200, router.stats())
            elif self.path == "/metrics":
                body = router.prometheus_metrics().encode()
                self.send_response(200)
                self.send_header("Content-Type", PROM_CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            else:
                self._send(404, {"error": "unknown path"})

        def do_POST(self):
            path = self.path.partition("?")[0]
            if path in ("/generate", "/v1/completions",
                        "/v1/chat/completions"):
                if kill_at and next(req_seq) == kill_at:
                    os.kill(os.getpid(), signal.SIGKILL)
                if router.draining:
                    # drain contract (scale-down/roll): NEW requests
                    # are refused loudly so the front door retries a
                    # surviving router; in-flight relays keep running
                    self._send(503, {"error": "router draining: retry "
                                              "another front door"})
                    return
            if path == "/generate":
                self._post_generate()
            elif path == "/v1/completions":
                self._post_openai(chat=False)
            elif path == "/v1/chat/completions":
                self._post_openai(chat=True)
            else:
                self._send(404, {"error": "unknown path"})

        def _read_json(self) -> dict:
            return read_json_body(self)

        def _route_stream(self, prompt, kwargs, frame_fn, final_fn,
                          error_fn) -> None:
            """The streaming relay glue both front-door surfaces share:
            SSE headers are sent LAZILY at the first forwarded token,
            so every pre-stream failure (429/503/504/400) still gets
            its proper HTTP status; failures after first byte go
            in-band. A vanished client surfaces as StreamConsumerError
            from the router — counted there, connection dropped here."""
            started = {"v": False}

            def on_tokens(toks):
                if not started["v"]:
                    self._begin_sse()
                    started["v"] = True
                self.wfile.write(frame_fn(toks))
                self.wfile.flush()

            try:
                resp = router.generate(prompt, on_tokens=on_tokens,
                                       **kwargs)
            except StreamConsumerError:
                self.close_connection = True
                return
            except FleetSaturatedError as e:
                if started["v"]:
                    self._stream_tail(error_fn(str(e)))
                else:
                    self._send(429, {"error": str(e)}, headers={
                        "Retry-After": str(e.retry_after_s)})
                return
            except NoReplicaError as e:
                if started["v"]:
                    self._stream_tail(error_fn(str(e)))
                else:
                    self._send(503, {"error": str(e)})
                return
            except TimeoutError as e:
                if started["v"]:
                    self._stream_tail(error_fn(str(e)))
                else:
                    self._send(504, {"error": str(e)})
                return
            except RouterClientError as e:
                if started["v"]:
                    self._stream_tail(error_fn(str(e)))
                else:
                    self._send(400, {"error": str(e)})
                return
            except RouterError as e:
                if started["v"]:
                    self._stream_tail(error_fn(str(e)))
                else:
                    self._send(502, {"error": str(e)})
                return
            if not started["v"]:    # zero-delta stream still terminates
                self._begin_sse()
                started["v"] = True
            self._stream_tail(final_fn(resp))

        def _stream_tail(self, data: bytes) -> None:
            try:
                self.wfile.write(data)
                self.wfile.flush()
            except OSError:
                pass                # client left during the tail write
            self.close_connection = True

        def _post_generate(self):
            try:
                payload = self._read_json()
                # coerce HERE so a malformed prompt ({"prompt": 123},
                # strings, nested junk) is a 400, not an unhandled
                # exception out of route_key on the handler thread
                prompt = [int(t) for t in payload["prompt"]]
                kwargs = {
                    "max_new_tokens": int(payload.get("max_new_tokens",
                                                      64)),
                    "timeout_s": float(payload.get("timeout_s", 600.0)),
                }
                if not 0 < kwargs["timeout_s"] < float("inf"):
                    raise ValueError(
                        "timeout_s must be a positive finite number")
                for k, cast in (("temperature", float), ("top_k", int),
                                ("model", str)):
                    if payload.get(k) is not None:
                        kwargs[k] = cast(payload[k])
                if payload.get("cache_prompt") is not None:
                    if not isinstance(payload["cache_prompt"], bool):
                        raise ValueError(
                            "cache_prompt must be a JSON boolean")
                    kwargs["cache_prompt"] = payload["cache_prompt"]
                if payload.get("stop") is not None:
                    if not isinstance(payload["stop"], list):
                        raise ValueError(
                            "stop must be a list of token ids or a "
                            "list of token-id lists")
                    kwargs["stop"] = payload["stop"]
                lp = payload.get("logprobs", 0) or 0
                if isinstance(lp, bool) or not isinstance(lp, int):
                    raise ValueError("logprobs must be an integer")
                if lp:
                    kwargs["logprobs"] = lp
                pri = payload.get("priority")
                if pri is not None:
                    if pri not in ("interactive", "batch"):
                        raise ValueError(
                            "priority must be 'interactive' or 'batch'")
                    kwargs["priority"] = pri
                reqid = payload.get("request_id")
                if reqid is not None:
                    # the id becomes a /progress URL key: constrain it
                    # to URL-safe chars and a sane length
                    if (not isinstance(reqid, str) or not re.fullmatch(
                            r"[A-Za-z0-9_.\-]{1,64}", reqid)):
                        raise ValueError(
                            "request_id must be 1-64 characters of "
                            "[A-Za-z0-9_.-]")
                    kwargs["request_id"] = reqid
                from .api.stream import stream_requested

                stream_on = stream_requested(payload, self.path)
                if stream_on and self.headers.get("Last-Event-ID"):
                    # SSE reconnect pass-through (docs/serving.md "SSE
                    # reconnect"): forwarded to the first replica
                    # attempt; the replica that parked the prefix
                    # resumes it, any other starts fresh
                    kwargs["last_event_id"] = \
                        self.headers.get("Last-Event-ID")
            except (KeyError, ValueError, TypeError) as e:
                self._send(400, {"error": str(e)})
                return
            # distributed-trace context for this door: adopt the
            # client's header if it sent one, else root it — derived
            # from request_id when given, so a failover re-POST at
            # another door joins the SAME trace
            ctx = TraceContext.from_header(
                self.headers.get(TRACE_HEADER))
            if ctx is None:
                ctx = (TraceContext.for_request_id(reqid)
                       if reqid is not None else TraceContext.mint())
            kwargs["trace"] = ctx
            if stream_on:
                sent = {"n": 0}

                def frame(toks):
                    sent["n"] += len(toks)
                    return sse_frame({"tokens": [int(t) for t in toks]})

                def final(resp):
                    return sse_frame({
                        "id": resp.get("id"),
                        "finish_reason": resp.get("finish_reason"),
                        "n_tokens": sent["n"],
                        "replica": resp.get("replica"),
                        "retries": resp.get("retries"),
                        "trace_id": ctx.trace_id})

                def err(msg):
                    return sse_frame({"error": str(msg)})

                self._route_stream(prompt, kwargs, frame, final, err)
                return
            try:
                resp = router.generate(prompt, **kwargs)
            except FleetSaturatedError as e:
                self._send(429, {"error": str(e)},
                           headers={"Retry-After": str(e.retry_after_s)})
                return
            except NoReplicaError as e:
                self._send(503, {"error": str(e)})
                return
            except TimeoutError as e:
                self._send(504, {"error": str(e)})
                return
            except RouterClientError as e:
                self._send(400, {"error": str(e)})
                return
            except RouterError as e:
                self._send(502, {"error": str(e)})
                return
            self._send(200, resp, headers={
                TRACE_ID_RESPONSE_HEADER: ctx.trace_id})

        def _post_openai(self, chat: bool):
            """The fleet-wide OpenAI-compatible surface: same payload
            contract as the per-replica /v1 endpoints (api.openai, the
            api-contract lint), routed/spilled/failed-over like every
            other request — one URL fronts the whole fleet."""
            from .api import openai as oai

            try:
                payload = self._read_json()
                req = (oai.parse_chat_request(payload, codec) if chat
                       else oai.parse_completion_request(payload, codec))
            except (KeyError, ValueError, TypeError) as e:
                self._send(400, {"error": {
                    "message": str(e), "type": "invalid_request_error"}})
                return
            model_name = req["model"] or router.fleet_model_fallback()
            kwargs = {"max_new_tokens": req["max_new_tokens"],
                      "timeout_s": req["timeout_s"]}
            if req.get("temperature") is not None:
                kwargs["temperature"] = req["temperature"]
            if req.get("top_k") is not None:
                kwargs["top_k"] = req["top_k"]
            if req["model"] is not None:
                kwargs["model"] = req["model"]
            if req.get("stop_sequences"):
                kwargs["stop"] = req["stop_sequences"]
            if req.get("logprobs"):
                kwargs["logprobs"] = req["logprobs"]
            if req.get("priority"):
                kwargs["priority"] = req["priority"]
            prompt = req["prompt_tokens"]
            rid = next(oai_ids)
            ctx = TraceContext.from_header(
                self.headers.get(TRACE_HEADER)) or TraceContext.mint()
            kwargs["trace"] = ctx
            if req["stream"] and self.headers.get("Last-Event-ID"):
                # SSE reconnect pass-through, same as /generate
                kwargs["last_event_id"] = \
                    self.headers.get("Last-Event-ID")
            if req["stream"]:
                frame, close, err = oai.stream_frame_fns(
                    rid, model_name, codec, chat,
                    trace_id=ctx.trace_id)
                self._route_stream(
                    prompt, kwargs, frame,
                    lambda resp: close(resp.get("finish_reason",
                                                "stop")),
                    err)
                return
            try:
                resp = router.generate(prompt, **kwargs)
            except FleetSaturatedError as e:
                self._send(429, {"error": {
                    "message": str(e), "type": "rate_limit_error"}},
                    headers={"Retry-After": str(e.retry_after_s)})
                return
            except NoReplicaError as e:
                self._send(503, {"error": {
                    "message": str(e), "type": "service_unavailable"}})
                return
            except TimeoutError as e:
                self._send(504, {"error": {
                    "message": str(e), "type": "timeout"}})
                return
            except RouterClientError as e:
                self._send(400, {"error": {
                    "message": str(e), "type": "invalid_request_error"}})
                return
            except RouterError as e:
                self._send(502, {"error": {
                    "message": str(e), "type": "server_error"}})
                return
            build = (oai.chat_response if chat
                     else oai.completion_response)
            # the ROUTER-local rid, not the replica's engine id: two
            # replicas' engines count independently (and restart from
            # zero), so replica ids collide across the fleet
            self._send(200, build(
                rid, model_name, resp.get("tokens", []),
                resp.get("finish_reason", "stop"), len(prompt), codec,
                logprobs=resp.get("logprobs")),
                headers={TRACE_ID_RESPONSE_HEADER: ctx.trace_id})

    return Handler


def build_argparser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tony-tpu route")
    p.add_argument("--port", type=int, default=8100)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--replica", action="append", default=[],
                   metavar="HOST:PORT",
                   help="static replica endpoint, repeatable (skip for "
                        "--job-dir discovery)")
    p.add_argument("--job-dir", default="",
                   help="a serving job's dir: discover replicas from the "
                        "driver (driver.json -> get_task_infos + the "
                        "serve_port each replica published)")
    p.add_argument("--role", default="",
                   help="with --job-dir: route only this role's tasks "
                        "(default: any task publishing a serve_port)")
    p.add_argument("--prefill-chunk", type=int, default=128,
                   help="the fleet's serve --prefill-chunk: affinity "
                        "keys hash chunk-ALIGNED prompt blocks, so this "
                        "must match for sticky routing to line up with "
                        "the replicas' prefix caches")
    p.add_argument("--no-affinity", action="store_true",
                   help="disable prefix-affinity: always least-loaded")
    p.add_argument("--health-interval-s", type=float, default=0.5)
    p.add_argument("--eject-after", type=int, default=2,
                   help="consecutive failed /healthz probes before a "
                        "replica is ejected from rotation")
    p.add_argument("--probe-timeout-s", type=float, default=2.0,
                   help="per-probe /healthz//stats timeout; raise it on "
                        "saturated replicas (a busy server answering "
                        "slowly must not read as dead)")
    p.add_argument("--spill-queue-depth", type=int, default=0,
                   help="treat a replica this many requests deep in "
                        "backlog as saturated (affinity spills to the "
                        "rendezvous runner-up); 0 = only trust 429s "
                        "and the replica's own max_queue")
    p.add_argument("--stats-every", type=int, default=4,
                   help="refresh each replica's /stats only every Nth "
                        "health tick (a /stats render takes the "
                        "replica's serving lock)")
    p.add_argument("--stats-offset", type=int, default=-1,
                   help="which tick (mod --stats-every) pulls /stats; "
                        "-1 derives a per-instance phase from the "
                        "router nonce so N routers stagger their "
                        "scrapes instead of phase-locking them")
    p.add_argument("--discovery-min-interval-s", type=float, default=2.0,
                   help="cache a successful driver-discovery read this "
                        "long (jittered): N routers must not hammer "
                        "get_task_infos at health-poll cadence; failed "
                        "reads back off exponentially on their own")
    p.add_argument("--drain-timeout-s", type=float, default=30.0,
                   help="on SIGTERM/SIGINT, stop accepting new "
                        "front-door requests and wait this long for "
                        "in-flight relays (streams included) to finish "
                        "before exiting 0 — the scale-down/roll drain "
                        "contract, mirroring serve")
    p.add_argument("--discovery-grace-s", type=float, default=10.0,
                   help="distrust an EMPTY discovery result this long "
                        "while live replicas still answer their own "
                        "probes (a dead or freshly recovered driver "
                        "must not drop a serving fleet); failed "
                        "discovery always keeps the last-known fleet")
    p.add_argument("--trace-dir", default="",
                   help="dump router request traces as JSONL "
                        "(requests.trace.jsonl) into this directory")
    p.add_argument("--text-codec", default="ids", choices=("ids", "bytes"),
                   help="text<->token mapping for the OpenAI-compatible "
                        "/v1 endpoints (must match the fleet's serve "
                        "--text-codec)")
    return p


def main(argv=None) -> int:
    import os
    from http.server import ThreadingHTTPServer

    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s router %(name)s: %(message)s")
    args = build_argparser().parse_args(argv)
    if not args.replica and not args.job_dir:
        raise SystemExit("need --replica endpoints or a --job-dir to "
                         "discover them from")
    discover = None
    if args.job_dir:
        from . import constants as c

        # under an executor (the `router` framework) ENV_TOKEN is the
        # driver's pre-derived executor-role key, not the root secret
        as_task = os.environ.get(c.ENV_TASK_INDEX) is not None
        discover = DriverDiscovery(
            args.job_dir, role=args.role or None,
            token=os.environ.get(c.ENV_TOKEN, ""),
            min_interval_s=args.discovery_min_interval_s,
            token_role="executor" if as_task else "client")
    trace_writer = None
    trace_sink = None
    if args.trace_dir:
        from .events.trace import TraceWriter

        trace_writer = TraceWriter(args.trace_dir)
        trace_sink = trace_writer.write
        print(f"router traces -> {trace_writer.path}", flush=True)
    router = FleetRouter(
        args.replica, prefill_chunk=args.prefill_chunk,
        affinity=not args.no_affinity,
        health_interval_s=args.health_interval_s,
        eject_after=args.eject_after,
        probe_timeout_s=args.probe_timeout_s,
        spill_queue_depth=args.spill_queue_depth or None,
        stats_every=args.stats_every, discover=discover,
        trace_sink=trace_sink,
        discovery_grace_s=args.discovery_grace_s,
        stats_phase=(None if args.stats_offset < 0
                     else args.stats_offset))
    router.start()
    from .api.openai import TokenCodec

    httpd = ThreadingHTTPServer((args.host, args.port),
                                make_handler(router,
                                             TokenCodec(args.text_codec)))

    # graceful drain on SIGTERM/SIGINT, mirroring serve's contract: a
    # driver-initiated roll/scale-down must stop accepting new
    # front-door requests, finish relaying in-flight streams (bounded
    # by --drain-timeout-s), then exit 0 — so a router scale-down is
    # zero-dropped by construction. A SECOND signal force-exits; the
    # drain runs on a helper thread (httpd.shutdown() deadlocks from
    # the serve_forever thread, and handlers must return fast).
    # Handlers install BEFORE the readiness print: a supervisor that
    # TERMs the instant it sees the routing line must hit the drain
    # path, not the default-action kill.
    import signal as _signal

    draining = threading.Event()

    def _drain_and_stop():
        router.drain(args.drain_timeout_s)
        httpd.shutdown()

    def _on_signal(signum, frame):
        if draining.is_set():
            print("second signal: exiting immediately", flush=True)
            os._exit(128 + signum)
        draining.set()
        print(f"signal {signum}: draining (finishing in-flight "
              f"relays, up to {args.drain_timeout_s}s)", flush=True)
        threading.Thread(target=_drain_and_stop, daemon=True).start()

    _signal.signal(_signal.SIGTERM, _on_signal)
    _signal.signal(_signal.SIGINT, _on_signal)
    print(f"routing on http://{args.host}:{httpd.server_address[1]} "
          f"({len(router.replicas)} static replicas"
          + (", driver discovery on" if discover else "") + ")",
          flush=True)
    try:
        httpd.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        httpd.server_close()
        router.shutdown()
        if discover is not None:
            discover.close()
        if trace_writer is not None:
            trace_writer.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
