"""Warm executor pool — pre-warmed children that ADOPT a task instead of
cold-starting it.

BENCH r05 measured a cold submit->first-step of 29.3s, of which 23.6s is
the training child paying `import jax` + backend init + data staging
(`launch_cold.backend_and_data_s`) — a bill charged again on every
restart-budget relaunch, preempt/resize/roll relaunch, and fleet
scale-up. This module keeps N STANDBY Python children per host that have
already prepaid exactly that bill (PAPER.md's NotebookSubmitter/
standalone mode is the precedent for pre-provisioned task processes that
adopt work instead of cold-starting):

- A standby (`python -m tony_tpu.warmpool --pool-dir ...`) imports jax,
  initializes the default backend (plus an optional user warmup hook,
  ``tony.warmpool.warmup-module`` — e.g. dataset staging to local disk),
  advertises itself in the pool directory, and blocks on a unix-socket
  control pipe.
- A task launch (runtimes/base.spawn_or_adopt) hands a ready standby the
  full task contract — env, command, cwd, log targets — over that pipe;
  the standby REPLACES its environment with the contract's, redirects
  stdout/stderr onto the container log, and execs the role's python
  entrypoint in-process via runpy, keeping the warm interpreter.
  ``jax.distributed.initialize`` is deliberately deferred to adoption
  time: coordinator/world info only exists once the gang barrier opens,
  so only the import/backend/data bill is prepaid (train/bootstrap.py's
  ``init()`` runs inside the adopted entrypoint as usual).
- A pool miss (no ready standby, non-python command, env-fingerprint
  mismatch, handshake failure) degrades to the cold ``Popen`` path —
  never to a failed launch. Container mode stays cold.

Claiming is an atomic ``os.rename`` of the standby's ready file, so
concurrent executors on one host never adopt the same standby. Standbys
run in their OWN sessions (they must survive the executor attempt that
spawned them — surviving attempts is the point), which makes reaping a
contract of its own:

- an ADOPTED child watches its adopter over the control socket and
  SIGKILLs itself on EOF — the moral equivalent of the process-group
  kill a cold in-group child would have received;
- an IDLE standby self-exits when its pool entry disappears (driver
  teardown removes the pool dir; shared-FS hosts see it too) or when the
  watched driver pid dies;
- ``WarmPool.reap()`` (driver ``stop()``) signals every same-host entry
  pid and removes the pool dir.

Executor-side accounting rides the task trace: ``child_adopted`` (pool
hit) or ``child_spawned`` with a ``warm_pool: miss`` attr; the driver
counts both into ``driver_warm_pool_{adoptions,misses}_total`` and
gauges ready standbys as ``driver_warm_pool_size`` (docs/
observability.md, docs/performance.md "Launch path").

The module is importable from the stdlib-only executor (``python -S``):
jax is imported only inside the standby's warmup.
"""

from __future__ import annotations

import argparse
import importlib
import json
import logging
import os
import re
import runpy
import select
import shlex
import signal
import socket
import subprocess
import sys
import threading
import time
from pathlib import Path
from typing import Any

from . import constants as c
from .conf import keys

log = logging.getLogger(__name__)

READY_SUFFIX = ".json"            # sb_<pid>.json: warmed, adoptable
CLAIMED_SUFFIX = ".json.claimed"  # mid-handshake (renamed by the claimer)
# how long a standby waits for the handshake after seeing itself claimed
# before assuming the claimer died and re-advertising (the real
# handshake follows the claim within milliseconds)
CLAIM_HANDSHAKE_S = 30.0
WARMING_SUFFIX = ".warming"       # spawned, still prepaying the bill
SOCK_SUFFIX = ".sock"
# backend-selection env the standby bakes in at warmup: a contract whose
# values differ would run on the wrong backend inside a pre-initialized
# interpreter, so a mismatch is a pool MISS, not a wrong adoption
ENV_FINGERPRINT_KEYS = ("JAX_PLATFORMS", "XLA_FLAGS", "TPU_CHIPS_PER_HOST_BOUNDS")
# how long a post-adoption replenishment waits before spawning the
# replacement standby: an immediate spawn's jax import + warmup competes
# with the freshly ADOPTED child's own first-step compile for host CPU
# (measured +3.5s submit->first-step on a 2-core host). The pool refills
# BETWEEN launches, not during them. Env-overridable (tests set 0).
REPLENISH_DELAY_ENV = "TONY_WARMPOOL_REPLENISH_DELAY_S"


def replenish_delay_s() -> float:
    try:
        return max(0.0, float(os.environ.get(REPLENISH_DELAY_ENV, "10")))
    except ValueError:
        return 10.0


# raw shell syntax the in-process runner cannot honor (plain $VAR
# expansion it CAN — expanded against the contract env at adoption)
_SHELL_META = re.compile(r"[|&;<>`]|\$\(")
_ENV_ASSIGN = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*=")
_PY_SKIP_FLAGS = frozenset({"-u", "-E", "-s", "-S", "-O", "-OO", "-B", "-I"})
_PY_ARG_FLAGS = frozenset({"-X", "-W"})


def _pid_alive(pid: int) -> bool:
    """Liveness that treats a ZOMBIE as dead: a long-lived spawner
    (the driver seeding the pool) holds its standbys as unreaped
    children, and a kill(pid, 0) would call the corpse alive."""
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    try:
        with open(f"/proc/{pid}/stat") as f:
            # field 3 is the state letter; the comm field before it may
            # itself contain spaces/parens, so split after the LAST ')'
            return f.read().rpartition(")")[2].split()[0] != "Z"
    except (OSError, IndexError):
        return True
    return True


def _is_standby_pid(pid: int) -> bool:
    """Does this pid still belong to a warm-pool process? Entry pids are
    only ever signalled after this check: a standby that died and had
    its pid RECYCLED by an unrelated service must not be killed on the
    strength of a stale pool file (host-level pools live for days).
    Adopted children keep their original argv in /proc, so the check
    stays true across adoption."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return b"tony_tpu.warmpool" in f.read()
    except OSError:
        return False


def _write_json_atomic(path: Path, payload: dict) -> None:
    tmp = Path(str(path) + ".tmp")
    tmp.write_text(json.dumps(payload))
    os.replace(tmp, path)


def _unlink(path: Path) -> None:
    try:
        path.unlink()
    except OSError:
        pass


# --------------------------------------------------------------- command parse
def parse_python_command(command: str) -> dict[str, Any] | None:
    """Is this role command a single python invocation the standby can run
    in-process? Returns ``{"module"|"script", "args", "env"}`` or None.

    Adoptable: ``[VAR=val ...] python[3[.x]] [-u -X... -W...] (-m mod |
    script.py) args...``. Plain ``$VAR`` references are fine (expanded
    against the contract env at adoption, mirroring what ``bash -c``
    would have done); pipelines/compound commands/substitutions are not
    — those genuinely need a shell and stay on the cold path."""
    if _SHELL_META.search(command):
        return None
    try:
        tokens = shlex.split(command)
    except ValueError:
        return None
    env: dict[str, str] = {}
    i = 0
    while i < len(tokens) and _ENV_ASSIGN.match(tokens[i]):
        k, _, v = tokens[i].partition("=")
        env[k] = v
        i += 1
    if i >= len(tokens):
        return None
    prog = os.path.basename(tokens[i])
    if not (prog == "python" or prog.startswith("python3")
            or tokens[i] == sys.executable):
        return None
    i += 1
    module = script = None
    while i < len(tokens):
        t = tokens[i]
        if t == "-m":
            if i + 1 >= len(tokens):
                return None
            module = tokens[i + 1]
            i += 2
            break
        if t in _PY_SKIP_FLAGS:
            i += 1
            continue
        if t in _PY_ARG_FLAGS:
            i += 2
            continue
        if t.startswith("-"):       # -c payloads and unknown flags: cold
            return None
        script = t
        i += 1
        break
    if module is None and script is None:
        return None
    return {"module": module, "script": script, "args": tokens[i:],
            "env": env}


def env_compatible(info: dict, contract_env: dict) -> bool:
    """May a standby described by ``info`` (its ready file) run a task
    with ``contract_env``? Only standbys that actually warmed a backend
    are fingerprint-bound; a skip-warmup standby (tests) is a blank
    interpreter and takes anything."""
    if "warmup" not in info:
        return True
    fp = info.get("env_fingerprint") or {}
    for key in ENV_FINGERPRINT_KEYS:
        if str(fp.get(key, "") or "") != str(contract_env.get(key, "") or ""):
            return False
    return True


# ------------------------------------------------------------- adopted handle
class AdoptedChild:
    """Popen-shaped handle on a standby that adopted this task.

    The adopter is NOT the standby's parent, so exit status travels over
    the control socket (``{"exit": code}`` sent just before the standby
    ``os._exit``s). EOF without a report means the standby was killed
    outright — reported as EXIT_KILLED, the same code the provisioner's
    group SIGKILL gives a cold child. Signals go by pid."""

    def __init__(self, pid: int, sock: socket.socket,
                 warmed_s: float = 0.0):
        self.pid = pid
        self.returncode: int | None = None
        self.warmed_s = warmed_s
        self._sock = sock
        self._sock.setblocking(False)
        self._buf = b""
        self._eof = False

    def poll(self) -> int | None:
        if self.returncode is not None:
            return self.returncode
        while not self._eof:
            try:
                chunk = self._sock.recv(4096)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._eof = True
                break
            if not chunk:
                self._eof = True
                break
            self._buf += chunk
        for line in self._buf.split(b"\n"):
            if not line.strip():
                continue
            try:
                msg = json.loads(line)
            except ValueError:
                continue
            if isinstance(msg, dict) and isinstance(msg.get("exit"), int):
                self.returncode = msg["exit"]
        if self.returncode is None and self._eof and not _pid_alive(self.pid):
            self.returncode = c.EXIT_KILLED
        return self.returncode

    def wait(self, timeout: float | None = None) -> int:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            rc = self.poll()
            if rc is not None:
                return rc
            if deadline is not None and time.monotonic() >= deadline:
                raise subprocess.TimeoutExpired(
                    f"adopted:{self.pid}", timeout)
            if self._eof:
                # the socket is gone but the pid lives (a child that
                # closed inherited fds): select on an EOF'd socket
                # returns readable instantly — poll the pid instead of
                # busy-spinning a core
                time.sleep(0.2)
                continue
            try:
                select.select([self._sock], [], [], 0.2)
            except OSError:
                time.sleep(0.05)

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)

    def _signal(self, sig: int) -> None:
        try:
            os.kill(self.pid, sig)
        except (ProcessLookupError, PermissionError):
            pass


# --------------------------------------------------------------------- pool
def _driver_json_pid(path: str | Path) -> int:
    """The driver pid advertised by a driver.json file, usable as a
    liveness watch ONLY when the driver runs on this host (loopback RPC
    endpoint) — a remote pid number would alias an unrelated local
    process."""
    try:
        info = json.loads(Path(path).read_text())
    except (OSError, ValueError):
        return 0
    if info.get("host") not in ("127.0.0.1", "localhost", "::1"):
        return 0
    pid = info.get("pid")
    return pid if isinstance(pid, int) and pid > 0 else 0


def _driver_watch_pid(job_dir: str) -> int:
    """The driver pid from the job dir's driver.json (see
    ``_driver_json_pid``)."""
    if not job_dir:
        return 0
    return _driver_json_pid(Path(job_dir) / c.DRIVER_INFO_FILE)


def count_ready(pool_dir: str | Path | None) -> int:
    """Live, unclaimed standbys in the pool (drives the
    ``driver_warm_pool_size`` gauge)."""
    if not pool_dir:
        return 0
    n = 0
    try:
        entries = sorted(Path(pool_dir).glob("sb_*" + READY_SUFFIX))
    except OSError:
        return 0
    for path in entries:
        try:
            info = json.loads(path.read_text())
        except (OSError, ValueError):
            continue
        pid = info.get("pid")
        if isinstance(pid, int) and _pid_alive(pid) and _is_standby_pid(pid):
            n += 1
    return n


class WarmPool:
    """Host-side view of one pool directory: spawn standbys up to the
    configured size, adopt from it, reap it at teardown."""

    def __init__(self, pool_dir: str | Path, size: int,
                 warmup_module: str = "", watch_pid: int = 0,
                 spawn_env: dict[str, str] | None = None,
                 driver_json: str = "", outage_grace_s: float = 30.0):
        self.dir = Path(pool_dir)
        self.size = int(size)
        self.warmup_module = warmup_module
        self.watch_pid = int(watch_pid)
        self.spawn_env = dict(spawn_env or {})
        # driver-outage tolerance for per-job pools: when the watched
        # driver pid dies, standbys re-resolve this driver.json for the
        # RECOVERED driver's pid for outage_grace_s before self-reaping
        # — a recovered driver finds its pool warm instead of cold
        self.driver_json = str(driver_json or "")
        self.outage_grace_s = float(outage_grace_s)
        # Popen handles of standbys THIS process spawned: polled on every
        # scan so exited standbys are reaped instead of lingering as
        # zombies under a long-lived spawner (the driver)
        self._procs: list[subprocess.Popen] = []

    # -------------------------------------------------------- construction
    @classmethod
    def from_conf(cls, conf, job_dir: str,
                  spawn_env: dict[str, str] | None = None) -> "WarmPool | None":
        """None when the pool is off (size<=0) or has nowhere to live."""
        if conf is None:
            return None
        try:
            size = conf.get_int(keys.WARMPOOL_SIZE, 0)
        except (TypeError, ValueError):
            return None
        if size <= 0:
            return None
        pool_dir = str(conf.get(keys.WARMPOOL_DIR, "") or "")
        watch_pid = 0
        driver_json = ""
        if not pool_dir:
            if not job_dir:
                return None
            pool_dir = os.path.join(str(job_dir), c.WARMPOOL_DIR_NAME)
            # per-JOB pool: standbys die with the job's driver; an
            # explicit tony.warmpool.dir is host-level capacity shared
            # across submits and must outlive any one driver. The
            # driver.json path lets standbys survive a driver RESTART:
            # they re-resolve the recovered driver's pid from it for the
            # outage grace before self-reaping.
            watch_pid = _driver_watch_pid(str(job_dir))
            driver_json = os.path.join(str(job_dir), c.DRIVER_INFO_FILE)
        try:
            grace_s = conf.get_int(keys.TASK_DRIVER_OUTAGE_GRACE_MS,
                                   30000) / 1000
        except (TypeError, ValueError):
            grace_s = 30.0
        return cls(
            pool_dir, size,
            warmup_module=str(conf.get(keys.WARMPOOL_WARMUP_MODULE, "") or ""),
            watch_pid=watch_pid,
            spawn_env=spawn_env,
            driver_json=driver_json,
            outage_grace_s=grace_s,
        )

    @classmethod
    def from_context(cls, ctx) -> "WarmPool | None":
        """Pool for an executor-side TaskContext (container mode stays
        cold — the adapter never calls this on that branch)."""
        job_dir = (ctx.base_child_env or {}).get(c.ENV_JOB_DIR, "")
        return cls.from_conf(ctx.conf, job_dir)

    # ------------------------------------------------------------ lifecycle
    def _entries(self) -> list[tuple[Path, dict]]:
        out = []
        try:
            paths = sorted(self.dir.iterdir())
        except OSError:
            return out
        for path in paths:
            if not path.name.startswith("sb_"):
                continue
            if path.name.endswith((".tmp", ".log", SOCK_SUFFIX)):
                continue
            try:
                info = json.loads(path.read_text())
            except (OSError, ValueError):
                info = {}
            out.append((path, info if isinstance(info, dict) else {}))
        return out

    def _live_count(self) -> int:
        """Ready + still-warming standbys; stale entries (dead pids) are
        swept on the way."""
        self._procs = [p for p in self._procs if p.poll() is None]
        n = 0
        for path, info in self._entries():
            pid = info.get("pid")
            alive = (isinstance(pid, int) and _pid_alive(pid)
                     and _is_standby_pid(pid))
            if path.name.endswith(CLAIMED_SUFFIX):
                if not alive:
                    _unlink(path)
                continue        # mid-adoption: already promised to a task
            if not alive:
                _unlink(path)
                if isinstance(pid, int):
                    _unlink(self.dir / f"sb_{pid}{SOCK_SUFFIX}")
                continue
            n += 1
        return n

    def ensure(self) -> int:
        """Top the pool up to ``size`` standbys; returns how many were
        spawned. Cheap when the pool is full (one directory scan).
        Serialized host-wide with an flock: a gang's co-hosted
        executors all ensure() at startup, and an unserialized
        scan-then-spawn would let each of them count the deficit before
        any warming marker lands — N executors × size over-spawned
        jax-loaded interpreters with nothing to ever trim them."""
        import fcntl

        self.dir.mkdir(parents=True, exist_ok=True)
        with open(self.dir / ".ensure.lock", "w") as lock:
            try:
                fcntl.flock(lock, fcntl.LOCK_EX)
            except OSError:
                pass        # no flock (exotic FS): racy over-spawn beats none
            # one scan up front: spawn_one writes a warming marker that
            # _live_count would immediately re-count
            needed = self.size - self._live_count()
            for _ in range(max(0, needed)):
                self.spawn_one()
        return max(0, needed)

    def spawn_one(self) -> int:
        """Start one standby in its own session; returns its pid. The
        warming marker is written here so a concurrent ensure() counts
        it before the standby finishes booting."""
        self.dir.mkdir(parents=True, exist_ok=True)
        argv = [sys.executable, "-m", "tony_tpu.warmpool",
                "--pool-dir", str(self.dir)]
        if self.warmup_module:
            argv += ["--warmup-module", self.warmup_module]
        if self.watch_pid:
            argv += ["--watch-pid", str(self.watch_pid)]
        if self.driver_json:
            argv += ["--driver-json", self.driver_json,
                     "--outage-grace-s", str(self.outage_grace_s)]
        env = {**os.environ, **self.spawn_env}
        # the standby must import tony_tpu no matter the spawner's cwd
        # (the executor may run from a localized work dir)
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = (
            pkg_root + ((os.pathsep + env["PYTHONPATH"])
                        if env.get("PYTHONPATH") else ""))
        log_path = self.dir / "spawn.log"
        # NOTE: no preexec_fn — forking python code from the driver's /
        # executor's threaded process can deadlock the child before
        # exec; the standby renices ITSELF first thing in standby_main
        with open(log_path, "ab") as out:
            proc = subprocess.Popen(
                argv, env=env, stdout=out, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
        self._procs.append(proc)
        _write_json_atomic(
            self.dir / f"sb_{proc.pid}{WARMING_SUFFIX}",
            {"pid": proc.pid, "host": socket.gethostname(),
             "t": time.time()})
        log.info("spawned warm standby pid=%d in %s", proc.pid, self.dir)
        return proc.pid

    # ------------------------------------------------------------- adoption
    def adopt(self, command: str, contract_env: dict[str, str],
              cwd: str | None = None) -> AdoptedChild | None:
        """Claim a ready standby and hand it the task contract. None on
        any miss (no standby, non-adoptable command, env mismatch,
        handshake failure) — the caller falls back to the cold spawn."""
        spec = parse_python_command(command)
        if spec is None:
            log.info("warm pool miss: command is not a single python "
                     "invocation")
            return None
        try:
            ready = sorted(self.dir.glob("sb_*" + READY_SUFFIX))
        except OSError:
            return None
        for path in ready:
            try:
                info = json.loads(path.read_text())
            except (OSError, ValueError):
                continue
            pid = info.get("pid")
            if (not isinstance(pid, int) or not _pid_alive(pid)
                    or not _is_standby_pid(pid)):
                _unlink(path)
                continue
            if not env_compatible(info, contract_env):
                log.info("warm pool: standby %d env fingerprint mismatch; "
                         "skipping", pid)
                continue
            claimed = Path(str(path) + ".claimed")
            try:
                os.rename(path, claimed)
            except OSError:
                continue        # another executor won the claim race
            try:
                child = self._handshake(info, command, contract_env, cwd)
            except Exception as e:
                log.warning("adoption of standby %d failed (%s); trying "
                            "the next one", pid, e)
                if _is_standby_pid(pid):    # never a recycled pid
                    try:
                        os.kill(pid, signal.SIGKILL)
                    except OSError:
                        pass
                _unlink(claimed)
                _unlink(self.dir / f"sb_{pid}{SOCK_SUFFIX}")
                continue
            log.info("adopted warm standby pid=%d (warmed %.1fs ago bill "
                     "prepaid in %.1fs)", pid,
                     time.time() - float(info.get("created", time.time())),
                     child.warmed_s)
            return child
        log.info("warm pool miss: no ready standby in %s", self.dir)
        return None

    def _handshake(self, info: dict, command: str,
                   contract_env: dict[str, str],
                   cwd: str | None) -> AdoptedChild:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(5.0)
        sock.connect(info["sock"])
        contract = {
            "command": command,
            "env": {str(k): str(v) for k, v in contract_env.items()},
            "cwd": cwd,
            "stdout_path": _fd_target(1),
            "stderr_path": _fd_target(2),
        }
        sock.sendall(json.dumps(contract).encode() + b"\n")
        sock.settimeout(15.0)
        # a fast child can exit before this read: the ack and the exit
        # report may arrive together — only the FIRST line is the ack,
        # the rest belongs to the AdoptedChild's stream
        line, rest = _recv_line(sock)
        ack = json.loads(line)
        if not (isinstance(ack, dict) and ack.get("ok")):
            raise RuntimeError(f"standby refused the contract: {ack}")
        sock.settimeout(None)
        child = AdoptedChild(int(info["pid"]), sock,
                             warmed_s=float(info.get("warmed_s", 0.0)))
        child._buf = rest
        return child

    # ----------------------------------------------------------------- reap
    def reap(self, grace_s: float = 2.0) -> None:
        """Teardown: signal every same-host entry pid (SIGTERM, then
        SIGKILL past the grace) and remove the pool directory. Entries
        from OTHER hosts (shared FS) only lose their files — their
        standbys notice the missing entry and self-exit; their pid
        numbers mean nothing here and are never signalled."""
        me = socket.gethostname()
        pids = []
        for path, info in self._entries():
            pid = info.get("pid")
            host = info.get("host", me)
            if (isinstance(pid, int) and host == me and _pid_alive(pid)
                    and _is_standby_pid(pid)):
                pids.append(pid)
            _unlink(path)
        for pid in pids:
            try:
                os.kill(pid, signal.SIGTERM)
            except OSError:
                pass
        deadline = time.monotonic() + grace_s
        while pids and time.monotonic() < deadline:
            pids = [p for p in pids if _pid_alive(p)]
            if pids:
                time.sleep(0.05)
        for pid in pids:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        for p in self._procs:       # reap our own corpses
            try:
                p.wait(timeout=1.0)
            except Exception:
                pass
        self._procs.clear()
        import shutil

        shutil.rmtree(self.dir, ignore_errors=True)


def _fd_target(fd: int) -> str | None:
    """Where this process's fd points, if it is a real file the standby
    can re-open (the container log the provisioner gave the executor).
    Pipes/sockets/ttys return None and the adopted child keeps writing
    to its standby log."""
    try:
        target = os.readlink(f"/proc/self/fd/{fd}")
    except OSError:
        return None
    return target if target.startswith("/") and os.path.exists(target) else None


def _recv_line(sock: socket.socket) -> tuple[bytes, bytes]:
    """Read up to the first newline; returns (line, leftover bytes that
    arrived with it)."""
    buf = b""
    while b"\n" not in buf:
        chunk = sock.recv(4096)
        if not chunk:
            break
        buf += chunk
    if not buf:
        raise RuntimeError("peer closed the control pipe mid-handshake")
    line, _, rest = buf.partition(b"\n")
    return line, rest


# ------------------------------------------------------------- standby process
def _default_warmup() -> dict:
    """The prepaid bill: import jax, initialize the default backend, and
    push one tiny jitted dispatch through it so the client, compiler
    plumbing, and transfer path are all live before adoption."""
    import jax
    import jax.numpy as jnp

    devices = jax.devices()
    jax.jit(lambda x: x + 1)(jnp.zeros((8,), jnp.float32)).block_until_ready()
    return {"devices": len(devices), "backend": jax.default_backend()}


_EXITING = False    # normal-exit fence for the adopter watchdog


def _watch_adopter(conn: socket.socket) -> None:
    """EOF on the control pipe means the adopter (executor) is gone: die
    the way a cold in-group child would have died with it. The fence
    keeps a normal exit's own socket shutdown from reading as adopter
    death."""
    try:
        while True:
            data = conn.recv(1)
            if not data:
                break
    except OSError:
        pass
    if _EXITING:
        return
    log.error("adopter vanished; killing the adopted child")
    os.kill(os.getpid(), signal.SIGKILL)


def _run_entrypoint(spec: dict) -> int:
    """Run the parsed python invocation in-process as ``__main__``."""
    os.environ.update(spec.get("env") or {})
    try:
        signal.signal(signal.SIGTERM, signal.SIG_DFL)
    except ValueError:
        pass
    # the standby warmed at background priority (standby_main); the
    # ADOPTED child is foreground work again. Lowering niceness needs
    # privilege (root / CAP_SYS_NICE — the usual TPU-VM runtime user);
    # elsewhere the child stays at nice 10, which only matters on an
    # oversubscribed host.
    try:
        os.setpriority(os.PRIO_PROCESS, 0, 0)
    except (OSError, AttributeError):
        pass
    sys.argv = [spec["module"] or spec["script"]] + list(spec["args"])
    try:
        if spec["module"]:
            runpy.run_module(spec["module"], run_name="__main__",
                             alter_sys=True)
        else:
            script = spec["script"]
            # a real `python script.py` puts the script's dir on sys.path
            sys.path.insert(0, os.path.dirname(os.path.abspath(script)))
            runpy.run_path(script, run_name="__main__")
        return 0
    except SystemExit as e:
        if e.code is None:
            return 0
        if isinstance(e.code, int):
            return e.code
        print(e.code, file=sys.stderr)
        return 1
    except BaseException:
        import traceback

        traceback.print_exc()
        return 1


def _redirect_output(stdout_path: str | None, stderr_path: str | None) -> None:
    """dup2 the task's log targets over the standby's fds so the adopted
    child's output lands where the cold child's would have."""
    for fd, path in ((1, stdout_path), (2, stderr_path)):
        if not path:
            continue
        try:
            target = os.open(path, os.O_WRONLY | os.O_APPEND | os.O_CREAT,
                             0o644)
            os.dup2(target, fd)
            os.close(target)
        except OSError as e:
            log.warning("could not redirect fd %d to %s: %s", fd, path, e)


def _serve_adoption(conn: socket.socket, pool_dir: Path, stem: str) -> int:
    """The standby's second life: apply the contract, become the task."""
    conn.settimeout(30.0)
    try:
        line, _ = _recv_line(conn)
        contract = json.loads(line)
        env = contract.get("env") or {}
        os.environ.clear()
        os.environ.update({str(k): str(v) for k, v in env.items()})
        cwd = contract.get("cwd")
        if cwd:
            os.chdir(cwd)
        _redirect_output(contract.get("stdout_path"),
                         contract.get("stderr_path"))
        # $VAR references the shell would have expanded are expanded here
        # against the freshly-applied contract env
        spec = parse_python_command(os.path.expandvars(contract["command"]))
        if spec is None:
            raise ValueError("command is not adoptable")
    except Exception as e:
        log.exception("adoption contract failed")
        try:
            conn.sendall(json.dumps({"ok": False, "error": str(e)}).encode()
                         + b"\n")
        except OSError:
            pass
        # env is possibly half-applied: this interpreter cannot go back
        # in the pool
        _cleanup_standby_files(pool_dir, stem)
        return 1
    conn.sendall(json.dumps({"ok": True, "pid": os.getpid()}).encode()
                 + b"\n")
    conn.settimeout(None)
    _cleanup_standby_files(pool_dir, stem)
    threading.Thread(target=_watch_adopter, args=(conn,),
                     name="adopter-watch", daemon=True).start()
    code = _run_entrypoint(spec)
    global _EXITING
    _EXITING = True
    try:
        sys.stdout.flush()
        sys.stderr.flush()
    except OSError:
        pass
    try:
        conn.sendall(json.dumps({"exit": code}).encode() + b"\n")
        conn.shutdown(socket.SHUT_RDWR)
        conn.close()
    except OSError:
        pass
    # _exit, not sys.exit: the entrypoint ran (and flushed) as __main__;
    # a second trip through this module's frames must not re-raise
    os._exit(code)


def _cleanup_standby_files(pool_dir: Path, stem: str) -> None:
    for suffix in (READY_SUFFIX, CLAIMED_SUFFIX, WARMING_SUFFIX, SOCK_SUFFIX):
        _unlink(pool_dir / (stem + suffix))


def standby_main(argv: list[str] | None = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s standby %(name)s: %(message)s",
    )
    parser = argparse.ArgumentParser(description="tony-tpu warm standby")
    parser.add_argument("--pool-dir", required=True)
    parser.add_argument("--warmup-module", default="")
    parser.add_argument("--watch-pid", type=int, default=0)
    parser.add_argument(
        "--driver-json", default="",
        help="path to the job's driver.json: when the watched pid dies, "
             "re-resolve a RECOVERED driver's pid from it for the outage "
             "grace before self-reaping (keeps the pool warm across a "
             "driver restart)")
    parser.add_argument("--outage-grace-s", type=float, default=30.0)
    args = parser.parse_args(argv)

    # a standby's warmup is BACKGROUND work and must yield the CPU to
    # live tasks (the replenish delay is the primary defense; this
    # covers seeding during first launches). Self-applied — a spawner-
    # side preexec_fn would fork python code under the driver's threads.
    try:
        os.nice(10)
    except OSError:
        pass
    pool_dir = Path(args.pool_dir)
    pool_dir.mkdir(parents=True, exist_ok=True)
    me = os.getpid()
    stem = f"sb_{me}"
    sock_path = pool_dir / (stem + SOCK_SUFFIX)
    ready_path = pool_dir / (stem + READY_SUFFIX)
    claimed_path = pool_dir / (stem + CLAIMED_SUFFIX)
    _unlink(sock_path)
    listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    listener.bind(str(sock_path))
    listener.listen(1)

    t0 = time.monotonic()
    info: dict[str, Any] = {
        "pid": me, "host": socket.gethostname(),
        "sock": str(sock_path), "created": time.time(),
    }
    if not os.environ.get(c.TEST_WARMPOOL_SKIP_WARMUP):
        try:
            info["warmup"] = _default_warmup()
            info["env_fingerprint"] = {
                k: os.environ.get(k, "") for k in ENV_FINGERPRINT_KEYS}
        except Exception as e:
            # an adoptable blank interpreter beats no standby at all
            log.warning("default warmup failed: %s", e)
            info["warmup_error"] = str(e)
    if args.warmup_module:
        try:
            mod = importlib.import_module(args.warmup_module)
            fn = getattr(mod, "warmup", None)
            if callable(fn):
                fn()
            info["warmup_module"] = args.warmup_module
        except Exception as e:
            log.warning("warmup module %s failed: %s", args.warmup_module, e)
            info["warmup_module_error"] = str(e)
    info["warmed_s"] = round(time.monotonic() - t0, 3)
    _write_json_atomic(ready_path, info)
    _unlink(pool_dir / (stem + WARMING_SUFFIX))
    log.info("standby %d ready in %s (warmed in %.1fs)", me, pool_dir,
             info["warmed_s"])

    listener.settimeout(1.0)
    conn = None
    claim_seen_t: float | None = None
    outage_t: float | None = None       # watched-driver death instant
    while conn is None:
        try:
            conn, _ = listener.accept()
        except socket.timeout:
            # self-reap: pool entry removed (teardown swept the dir) or
            # the watched driver died without a clean stop
            if not (ready_path.exists() or claimed_path.exists()):
                log.info("pool entry gone; standby %d exiting", me)
                _unlink(sock_path)
                return 0
            # claim-abandonment recovery: an adopter that died between
            # its claim rename and the handshake would otherwise park
            # this standby (and leak it in host-level pools, where no
            # driver reap runs) — put the entry back up for adoption
            if claimed_path.exists() and not ready_path.exists():
                if claim_seen_t is None:
                    claim_seen_t = time.monotonic()
                elif time.monotonic() - claim_seen_t > CLAIM_HANDSHAKE_S:
                    log.warning(
                        "claim abandoned (no handshake in %.0fs); "
                        "standby %d re-advertising", CLAIM_HANDSHAKE_S, me)
                    try:
                        os.rename(claimed_path, ready_path)
                    except OSError:
                        _cleanup_standby_files(pool_dir, stem)
                        return 0
                    claim_seen_t = None
            else:
                claim_seen_t = None
            if args.watch_pid and not _pid_alive(args.watch_pid):
                # driver-outage grace: a SIGKILLed driver's recovered
                # successor rewrites driver.json with ITS pid — adopt it
                # as the new watch target so the pool stays warm across
                # the restart; self-reap only once the grace runs dry
                new_pid = (_driver_json_pid(args.driver_json)
                           if args.driver_json else 0)
                if (new_pid and new_pid != args.watch_pid
                        and _pid_alive(new_pid)):
                    log.warning(
                        "watched driver %d died; re-watching recovered "
                        "driver %d (driver.json)", args.watch_pid, new_pid)
                    args.watch_pid = new_pid
                    outage_t = None
                    continue
                if outage_t is None and args.driver_json:
                    outage_t = time.monotonic()
                    log.warning(
                        "watched pid %d gone; standby %d riding the "
                        "%.1fs driver-outage grace", args.watch_pid, me,
                        args.outage_grace_s)
                if (outage_t is not None and time.monotonic() - outage_t
                        <= args.outage_grace_s):
                    continue
                log.info("watched pid %d gone; standby %d exiting",
                         args.watch_pid, me)
                _cleanup_standby_files(pool_dir, stem)
                return 0
            outage_t = None
        except OSError as e:
            log.error("control socket failed: %s", e)
            _cleanup_standby_files(pool_dir, stem)
            return 1
    listener.close()
    return _serve_adoption(conn, pool_dir, stem)


if __name__ == "__main__":
    sys.exit(standby_main())
