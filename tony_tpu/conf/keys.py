"""Configuration key constants and per-role key accessors.

Mirrors the reference's key registry (tony-core/.../TonyConfigurationKeys.java:1-339):
every global key has an entry in conf/defaults.json (cross-checked by
tests/test_config.py, the way TestTonyConfigurationFields.java cross-checks
tony-default.xml), and per-role keys are generated from templates so that new
roles (ps/worker/chief/evaluator/scheduler/head/driver/...) need no code change
(reference discovers roles by regex, TonyConfigurationKeys.java:189-191).
"""

from __future__ import annotations

import re

PREFIX = "tony."

# ---------------------------------------------------------------- application
APPLICATION_NAME = "tony.application.name"
APPLICATION_FRAMEWORK = "tony.application.framework"  # jax|tensorflow|pytorch|mxnet|horovod|standalone
APPLICATION_DISTRIBUTED_MODE = "tony.application.distributed-mode"  # GANG|FCFS
APPLICATION_TIMEOUT_MS = "tony.application.timeout-ms"  # 0 = no timeout
APPLICATION_TAGS = "tony.application.tags"
APPLICATION_PREPARE_STAGE = "tony.application.prepare-stage"
APPLICATION_TRAINING_STAGE = "tony.application.training-stage"
APPLICATION_UNTRACKED_JOBTYPES = "tony.application.untracked.jobtypes"
APPLICATION_STOP_ON_FAILURE_JOBTYPES = "tony.application.stop-on-failure-jobtypes"
APPLICATION_FAIL_ON_WORKER_FAILURE = "tony.application.fail-on-worker-failure-enabled"
APPLICATION_ENABLE_PREPROCESS = "tony.application.enable-preprocess"
APPLICATION_NODE_LABEL = "tony.application.node-label"

# --------------------------------------------------------------------- driver
AM_RETRY_COUNT = "tony.am.retry-count"
AM_MONITOR_INTERVAL_MS = "tony.am.monitor-interval-ms"
AM_RPC_HOST = "tony.am.rpc-host"
AM_REGISTRATION_TIMEOUT_MS = "tony.am.registration-timeout-ms"
AM_ALLOCATION_TIMEOUT_MS = "tony.am.allocation-timeout-ms"  # gang-deadlock breaker
# driver GET /metrics (Prometheus text): 0 = ephemeral port (advertised in
# driver.json next to the RPC endpoint), -1 = disabled
AM_METRICS_PORT = "tony.am.metrics-port"

# ---------------------------------------------------------------------- tasks
TASK_HEARTBEAT_INTERVAL_MS = "tony.task.heartbeat-interval-ms"
TASK_MAX_MISSED_HEARTBEATS = "tony.task.max-missed-heartbeats"
TASK_METRICS_INTERVAL_MS = "tony.task.metrics-interval-ms"
TASK_REGISTRATION_POLL_MS = "tony.task.registration-poll-interval-ms"
TASK_EXECUTOR_EXECUTION_TIMEOUT_MS = "tony.task.executor.execution-timeout-ms"
TASK_PORT_REUSE_ENABLED = "tony.task.port-reuse-enabled"      # SO_REUSEPORT rendezvous port
TASK_TB_PORT_REUSE_ENABLED = "tony.task.tb-port-reuse-enabled"  # SO_REUSEPORT TB port
TASK_MAX_TOTAL_INSTANCES = "tony.task.max-total-instances"
# drain grace for a preemption notice (heartbeat "preempting" command or
# an executor-received SIGTERM): how long the executor gives the training
# child to checkpoint at a step boundary before killing it
TASK_PREEMPT_GRACE_MS = "tony.task.preempt-grace-ms"
# driver-outage window (docs/training-robustness.md "Control-plane
# recovery"): how long an executor whose heartbeat RPCs fail at the
# TRANSPORT level keeps its training child stepping — re-resolving the
# driver endpoint from the rewritten driver.json each beat — before it
# gives up, checkpoint-drains the child, and exits. Warm-pool standbys
# honor the same window before self-reaping on a dead watched driver
# pid. In-contact refusals (the driver answered and said no) stay on
# the max-missed-heartbeats budget.
TASK_DRIVER_OUTAGE_GRACE_MS = "tony.task.driver-outage-grace-ms"
TASK_MAX_TOTAL_MEMORY_MB = "tony.task.max-total-memory-mb"
TASK_MAX_TOTAL_CHIPS = "tony.task.max-total-chips"

# -------------------------------------------------------------------- staging
STAGING_DIR = "tony.staging.dir"
HISTORY_DIR = "tony.history.location"
HISTORY_INTERMEDIATE = "tony.history.intermediate"
HISTORY_FINISHED = "tony.history.finished"
# bearer token gating every portal route ("" = open); the analogue of the
# reference portal living behind Hadoop-secured infra
# (tony-portal/app/hadoop/Requirements.java)
PORTAL_TOKEN = "tony.portal.token"
HISTORY_RETENTION_SEC = "tony.history.retention-sec"
HISTORY_MOVER_INTERVAL_MS = "tony.history.mover-interval-ms"
SRC_DIR = "tony.application.src-dir"
# job-archive shipping to remote executor hosts (reference HDFS staging,
# TonyClient.java:232-315): URI executors fetch the archive from, an optional
# client-side upload command template ({archive}, {uri}), and a per-task
# switch forcing fetch+unpack even when the path looks shared
APPLICATION_ARCHIVE_URI = "tony.application.archive-uri"
APPLICATION_ARCHIVE_UPLOAD_CMD = "tony.application.archive-upload-cmd"
# sha256 of the built archive, frozen at submit time and verified by every
# executor before unpack — the integrity role of the reference's token-secured
# HDFS staging (TonyClient.java:981-1030) on untrusted transports (http, gs)
APPLICATION_ARCHIVE_SHA256 = "tony.application.archive-sha256"
TASK_LOCALIZE = "tony.task.localize"
PYTHON_VENV = "tony.application.python-venv"
PYTHON_BINARY_PATH = "tony.application.python-binary-path"
EXECUTION_ENV = "tony.execution.env"  # list of K=V propagated to every task

# containerized task launch (reference Docker-on-YARN support: key names from
# TonyConfigurationKeys.java:245-290, wrapping from HadoopCompatibleAdapter
# .java:45-159; here the executor wraps the command itself)
DOCKER_ENABLED = "tony.docker.enabled"
DOCKER_IMAGE = "tony.docker.containers.image"   # image for all task processes
DOCKER_MOUNTS = "tony.docker.containers.mount"  # list of src:dst[:ro]
DOCKER_RUN_ARGS = "tony.docker.extra-args"      # list of extra docker-run flags


def docker_image_key(role: str) -> str:
    """Per-role image override (reference getDockerImageKey)."""
    return f"tony.docker.{role}.image"

# -------------------------------------------------------------------- secrets
SECURITY_TOKEN_ENABLED = "tony.security.token-enabled"

# ------------------------------------------------------------------- cluster
CLUSTER_PROVISIONER = "tony.cluster.provisioner"  # local|tpu-pod|static
CLUSTER_STATIC_HOSTS = "tony.cluster.static-hosts"
# {host}/{env} command template for static-host launches ("" = default ssh)
CLUSTER_LAUNCH_TEMPLATE = "tony.cluster.launch-template"
TPU_TOPOLOGY = "tony.tpu.topology"  # e.g. v5e-8; "" = discover
TPU_ACCELERATOR_TYPE = "tony.tpu.accelerator-type"
TPU_DISCOVER_COMMAND = "tony.tpu.discover-command"  # prints one worker host per line
# slice lifecycle (the RM capacity-allocation half, reference
# TonyClient.submitApplication:317-353 + async container grants,
# ApplicationMaster.java:1100-1119): command templates keep cloud CLIs out
# of core. create-command materializes the slice (e.g. `gcloud compute tpus
# tpu-vm create ...` or a queued-resources request); the driver then polls
# discover-command until the slice reports its full host complement
# (await-READY). delete-command tears down what the driver created — run at
# job end only for driver-created slices, and before re-creation when a
# preempted slice must be replaced.
TPU_CREATE_COMMAND = "tony.tpu.create-command"
TPU_DELETE_COMMAND = "tony.tpu.delete-command"
# >1 = the job spans N slices (multislice): each lifecycle/discover command
# template is instantiated once per slice with `{slice}` replaced by the
# slice index (0..N-1) — one cloud resource per slice — and executors get
# TONY_SLICE_ID / TONY_NUM_SLICES / TONY_SLICE0_HOST so the JAX runtime can
# bring up cross-slice (DCN) transport. Reference analogue: the RM granting
# containers across racks (ApplicationMaster.java:1100-1119).
TPU_NUM_SLICES = "tony.tpu.num-slices"
TPU_CREATE_TIMEOUT_S = "tony.tpu.create-timeout-s"  # await-READY deadline
TPU_CREATE_POLL_S = "tony.tpu.create-poll-interval-s"
# discovery attempts before the lifecycle path declares the slice gone and
# deletes+recreates — armor against one transient describe flake destroying
# healthy capacity
TPU_DISCOVER_RETRIES = "tony.tpu.discover-retries"
# regex matched against a failed discover-command's stderr: a match is
# positive "the cloud says the slice does not exist" evidence; only then
# (or on a successful-but-partial describe) may the lifecycle path
# delete+recreate. A nonzero exit that does NOT match (API 5xx, auth
# outage, timeout) aborts instead of destroying possibly-healthy capacity.
TPU_NOT_FOUND_PATTERN = "tony.tpu.not-found-pattern"
# consecutive identical host lists required to declare READY when no
# accelerator-type gives an exact host count (stalled partial endpoint
# lists can look stable briefly; more polls = stronger evidence)
TPU_READY_STABLE_POLLS = "tony.tpu.ready-stable-polls"

# ------------------------------------------------------------------ serving
# serving job type (tony.application.framework = serving): the executor-side
# adapter watches each replica child's /healthz and converts a terminally
# down serving loop into a container failure the driver's restart budget
# handles (runtimes/serving.py)
SERVING_HEALTHZ_INTERVAL_MS = "tony.serving.healthz-interval-ms"
# consecutive bad post-ready polls (503 down / unreachable) before the
# adapter kills the child and exits nonzero
SERVING_HEALTHZ_DOWN_POLLS = "tony.serving.healthz-down-polls"
# how long a replica gets from spawn to its first healthy /healthz before
# the adapter gives up (model load + first compile can dominate)
SERVING_READY_TIMEOUT_MS = "tony.serving.ready-timeout-ms"
# paged-KV serving (serve --paged-kv family; docs/serving.md "Paged KV &
# admission tiers"): replica launch commands templated from conf pick
# these up instead of hard-coding flags per job file
SERVING_PAGED_KV = "tony.serving.paged-kv"
SERVING_KV_BLOCK = "tony.serving.kv-block"
SERVING_KV_POOL_BLOCKS = "tony.serving.kv-pool-blocks"
SERVING_PREFILL_INTERLEAVE = "tony.serving.prefill-interleave"
SERVING_CLASS_BUDGET_INTERACTIVE = \
    "tony.serving.class-budget-interactive"
SERVING_CLASS_BUDGET_BATCH = "tony.serving.class-budget-batch"
SERVING_BATCH_QUEUE_FRAC = "tony.serving.batch-queue-frac"
# disaggregated prefill/decode serving (docs/serving.md "Disaggregated
# serving"): carve the replica gang into phase tiers by task index —
# the first P replicas launch with --role prefill (forcing --paged-kv:
# the KV block is the transfer unit), the next D with --role decode,
# the remainder --role both. 0/0 (default) = a uniform "both" fleet.
SERVING_PREFILL_INSTANCES = "tony.serving.prefill-instances"
SERVING_DECODE_INSTANCES = "tony.serving.decode-instances"

# ------------------------------------------------------------------ training
# elastic, preemption-tolerant training (docs/training-robustness.md):
# with elastic enabled, a worker lost beyond its restart budget detaches
# from the gang instead of failing the job — the driver bumps the gang
# generation, survivors drain (checkpoint) and re-register at the new
# world size, and the detached slot is retried every rescale-retry-ms
# until capacity returns (then the gang resizes back up).
TRAIN_ELASTIC_ENABLED = "tony.train.elastic-enabled"
# floor on the surviving world size: a resize that would drop the role
# below this (or lose the chief) fails the job like before
TRAIN_ELASTIC_MIN_INSTANCES = "tony.train.elastic-min-instances"
TRAIN_RESCALE_RETRY_MS = "tony.train.rescale-retry-ms"
# straggler action: a worker whose pushed step-time p50 exceeds
# factor x the role median gets a budget-charged restart through the
# normal _try_restart_task path. 0 disables (observation-only, the PR 5
# behavior); sane values start around 2-3.
TRAIN_STRAGGLER_RESTART_FACTOR = "tony.train.straggler-restart-factor"
# consecutive monitor checks a task must look slow before the restart
# fires (one noisy push must not cost a budget unit)
TRAIN_STRAGGLER_GRACE_CHECKS = "tony.train.straggler-grace-checks"

# ---------------------------------------------------------------- autoscaling
# closed-loop serving autoscaler (tony_tpu/autoscale.py, docs/
# autoscaling.md): a driver-resident controller watches the serving
# fleet's merged telemetry (per-replica /metrics TTFT buckets + /stats
# queue depths, optionally a router /stats) and scales the serving role
# between min and max replicas — scale-up launches a parked slot via the
# normal (warm-pool-adopting) launch path, scale-down SIGTERM-drains the
# least-loaded replica and parks its slot. Decisions are journaled so a
# recovered driver resumes mid-cooldown instead of flapping.
AUTOSCALE_ENABLED = "tony.autoscale.enabled"
# the serving role the controller scales ("" = the job's single role;
# multi-role jobs must name it)
AUTOSCALE_ROLE = "tony.autoscale.role"
# scale-up SLOs: windowed fleet TTFT p99 (seconds; 0 = ignore) and total
# queued requests across replicas (0 = ignore). Breaching EITHER for
# breach-ticks consecutive controller ticks triggers a scale-up.
AUTOSCALE_TTFT_P99_SLO_S = "tony.autoscale.ttft-p99-slo-s"
AUTOSCALE_QUEUE_DEPTH_SLO = "tony.autoscale.queue-depth-slo"
# decode-tier SLO for disaggregated fleets (docs/autoscaling.md
# "Two-tier scaling"): windowed fleet TPOT p99 in seconds/token (0 =
# ignore). On a fleet with role specialists, a queue breach scales the
# PREFILL tier while a TTFT/TPOT breach scales the DECODE tier.
AUTOSCALE_TPOT_P99_SLO_S = "tony.autoscale.tpot-p99-slo-s"
# replica-count bounds: min is the steady-state floor (the slots above
# it start PARKED — detached, unlaunched); max 0 = the role's instances
AUTOSCALE_MIN = "tony.autoscale.min"
AUTOSCALE_MAX = "tony.autoscale.max"
# hysteresis: no two scale decisions inside the cooldown, and scale-down
# additionally needs the signals CLEAR (below half the SLO) for a full
# cooldown — flapping costs drains, so the loop is deliberately sticky
AUTOSCALE_COOLDOWN_S = "tony.autoscale.cooldown-s"
# controller tick cadence (telemetry poll + decision)
AUTOSCALE_INTERVAL_S = "tony.autoscale.interval-s"
# consecutive breaching ticks before a scale-up fires (one noisy window
# must not launch capacity)
AUTOSCALE_BREACH_TICKS = "tony.autoscale.breach-ticks"
# optional fleet-router /stats URL merged into the controller's view
# (the router sees posted-but-unadmitted traffic the replicas' own
# stats lag on; the two views OVERLAP, so the control law takes their
# max, never the sum)
AUTOSCALE_ROUTER_STATS_URL = "tony.autoscale.router-stats-url"
# router-TIER scaling (docs/autoscaling.md "Three-tier scaling"): the
# role whose tasks are fleet routers ("" = auto-detect the role whose
# framework is "router"; the tier is scaled only when such a role
# exists), the per-router relay-inflight SLO that breaches it (mean of
# router_relay_inflight across live front doors; 0 = never scale the
# tier), and its steady-state floor (slots above the floor start
# parked, exactly like the serving role's)
AUTOSCALE_ROUTER_ROLE = "tony.autoscale.router-role"
AUTOSCALE_ROUTER_RELAY_SLO = "tony.autoscale.router-relay-slo"
AUTOSCALE_ROUTER_MIN = "tony.autoscale.router-min"

# ------------------------------------------------------- metrics hub / SLO
# fleet metrics pipeline (tony_tpu/metricshub.py) + SLO burn-rate
# alerting (tony_tpu/slo.py, docs/observability.md "Metrics pipeline &
# SLO alerting"). Objectives are DECLARATIVE, one per name:
#
#   tony.slo.<name>.objective    availability | ttft-p99 | tpot-p99
#   tony.slo.<name>.target       good/total promised (e.g. 0.99)
#   tony.slo.<name>.window-s     SLO horizon; the four alert windows
#                                derive from it (fast W/6+W/60, slow
#                                W+W/6) — bench/test clocks shrink it
#   tony.slo.<name>.threshold-s  latency objectives: the "good" bound
#   tony.slo.<name>.fast-burn    fast-pair burn threshold (14.4)
#   tony.slo.<name>.slow-burn    slow-pair burn threshold (6.0)
#
# <name> may not contain dots. The keys below tune the shared pipeline:
# the hub's own scrape cadence (used when no autoscaler tick is already
# driving the scrapes), its ring retention horizon, and the per-series
# point bound.
SLO_PREFIX = "tony.slo."
SLO_SCRAPE_INTERVAL_S = "tony.slo.scrape-interval-s"
SLO_HUB_RETENTION_S = "tony.slo.hub-retention-s"
SLO_HUB_MAX_POINTS = "tony.slo.hub-max-points"

# ------------------------------------------------------------------- quota
# multi-tenant arbitration (tony_tpu/autoscale.py ResourceArbiter): all
# roles share one device/slot pool; per-role quotas cap what each may
# hold, and priority classes decide who yields when the pool is
# exhausted — `interactive` (serving) capacity demands preempt `batch`
# (training) workers via the budget-free preempt drain, and batch
# reclaims the slots when the interactive tier scales back down.
# 0 = the sum of configured role instances (no oversubscription).
QUOTA_POOL_SLOTS = "tony.quota.pool-slots"

# ------------------------------------------------------------------ training
# checkpoint directory of the (elastic) training role, used by the
# checkpoint-aware rescale placement: a worker relaunched on the
# capacity-return path gets TONY_PRESTAGE_CKPT so its executor restores
# (pre-reads) the newest checkpoint BEFORE registering — the gang
# barrier opens onto a worker whose checkpoint bytes are already local.
# May reference task env vars (e.g. .../ckpt_$TONY_TASK_INDEX).
TRAIN_CKPT_DIR = "tony.train.checkpoint-dir"

# ----------------------------------------------------------------- warm pool
# warm executor pool (tony_tpu/warmpool.py, docs/performance.md "Launch
# path"): N standby python children per host that have already imported
# jax + initialized the backend; a task launch ADOPTS one instead of
# cold-spawning, cutting submit->first-step, relaunch, resize, and roll
# latency by the prepaid bill (BENCH r05: 23.6s of a 29.3s cold start).
# 0 disables (every launch spawns cold).
WARMPOOL_SIZE = "tony.warmpool.size"
# optional dotted module imported during standby warmup; its warmup()
# (if defined) runs after the default jax warmup — the hook for
# pre-staging data / prepaying heavyweight imports the role command needs
WARMPOOL_WARMUP_MODULE = "tony.warmpool.warmup-module"
# where the pool lives; "" = <job dir>/warmpool (per-job pool). Point
# several jobs at one host-level dir to share standbys across submits.
WARMPOOL_DIR = "tony.warmpool.dir"

# ------------------------------------------------------------------ horovod
HOROVOD_TEST_MODE = "tony.horovod.mode.test"              # stub rendezvous server
HOROVOD_FAST_FAIL = "tony.horovod.driver.fast-fail"       # driver exits 1 at once
HOROVOD_DEBUG_COMMAND = "tony.horovod.driver.debug-command"  # user-supplied driver
HOROVOD_DRIVER_START_TIMEOUT_MS = "tony.horovod.driver.start-timeout-ms"

# ----------------------------------------------------------- per-role templates
# reference: tony.<job>.{instances,memory,vcores,gpus,command,resources,
# node-label,depends-on,max-instances} (TonyConfigurationKeys.java getInstancesKey etc.)
ROLE_KEY_TEMPLATES = (
    "instances",
    "memory-mb",
    "vcores",
    "chips",       # replaces reference 'gpus' with TPU chips per task
    "command",
    "resources",
    "node-label",
    "depends-on",
    "max-instances",
    "env",
    "max-restarts",  # per-task restart budget — exceeds the reference, which
                     # only supports whole-job AM retry (SURVEY.md §5)
    "framework",     # per-role runtime override (multi-tenant jobs mix
                     # serving replicas with training workers; "" = the
                     # app-level tony.application.framework)
    "priority-class",  # arbiter tier: "interactive" (default) or "batch"
                       # — batch roles donate capacity to interactive
                       # ones under pool pressure (docs/autoscaling.md)
    "quota",         # max pool slots this role may hold (-1 = instances)
)

_ROLE_KEY_RE = re.compile(r"^tony\.([A-Za-z][A-Za-z0-9_\-]*)\.instances$")
_RESERVED_NON_ROLES = frozenset(
    # "router" is deliberately NOT reserved: the router tier is an
    # ordinary role (tony.router.instances, framework "router" —
    # docs/serving.md "Router tier HA"), and no global tony.router.*
    # keys exist to collide with it
    {"application", "am", "task", "staging", "history", "cluster", "tpu",
     "security", "execution", "horovod", "version", "serving",
     "train", "warmpool", "autoscale", "quota"}
)


def role_key(role: str, template: str) -> str:
    """tony.<role>.<template> — e.g. role_key('worker', 'instances')."""
    if template not in ROLE_KEY_TEMPLATES:
        raise KeyError(f"unknown role key template: {template}")
    return f"tony.{role}.{template}"


def instances_key(role: str) -> str:
    return role_key(role, "instances")


def command_key(role: str) -> str:
    return role_key(role, "command")


def depends_on_key(role: str) -> str:
    return role_key(role, "depends-on")


def discover_roles(conf_dict: dict) -> list[str]:
    """Find roles by scanning for tony.<role>.instances keys.

    Mirrors the reference's regex discovery (util/Utils.java:451-460) so
    arbitrary role names (ps, worker, chief, evaluator, scheduler, head,
    driver, tensorboard, notebook, ...) work without code changes.
    """
    roles = []
    for key in conf_dict:
        m = _ROLE_KEY_RE.match(key)
        if m and m.group(1) not in _RESERVED_NON_ROLES:
            roles.append(m.group(1))
    return sorted(roles)
