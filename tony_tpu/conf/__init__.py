"""Layered configuration system.

Mirrors the reference's Hadoop-Configuration stack (TonyClient.java:666-700):
defaults -> user config file(s) -> -conf k=v CLI overrides -> site config from
$TONY_CONF_DIR/tony-site.json. The fully-resolved config is frozen to
``tony-final.json`` in the job dir and localized to every task (reference
freezes tony-final.xml, Constants.java:148), so driver/executors/user code all
see one immutable snapshot.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterable, Mapping

from . import keys

_DEFAULTS_PATH = Path(__file__).parent / "defaults.json"

SITE_CONF_ENV = "TONY_CONF_DIR"
SITE_CONF_NAME = "tony-site.json"
FINAL_CONF_NAME = "tony-final.json"


def load_defaults() -> dict[str, Any]:
    with open(_DEFAULTS_PATH) as f:
        return json.load(f)


def _coerce(value: str) -> Any:
    """Coerce a CLI string override to bool/int/float when unambiguous."""
    low = value.lower()
    if low in ("true", "false"):
        return low == "true"
    for cast in (int, float):
        try:
            return cast(value)
        except ValueError:
            pass
    return value


@dataclass
class RoleSpec:
    """Parsed per-role request — reference models/JobContainerRequest.java."""

    name: str
    instances: int
    memory_mb: int = 2048
    vcores: int = 1
    chips: int = 0
    command: str = ""
    resources: list[str] = field(default_factory=list)
    node_label: str = ""
    depends_on: list[str] = field(default_factory=list)
    max_instances: int = -1
    max_restarts: int = 0
    env: dict[str, str] = field(default_factory=dict)
    priority: int = 0  # unique per role, like reference YARN priorities
    # per-role runtime override ("" = the app-level framework): a
    # multi-tenant job mixes serving replicas with training workers in
    # one session (docs/autoscaling.md)
    framework: str = ""
    # arbiter tier: interactive roles preempt batch roles' capacity
    # under pool pressure (tony_tpu/autoscale.py ResourceArbiter)
    priority_class: str = "interactive"
    # max pool slots this role may hold concurrently (-1 = instances)
    quota: int = -1


class TonyConf:
    """Immutable-ish layered config with role discovery and validation."""

    def __init__(self, data: Mapping[str, Any] | None = None):
        self._data: dict[str, Any] = dict(load_defaults())
        if data:
            self._data.update(data)

    # ------------------------------------------------------------- layering
    @classmethod
    def resolve(
        cls,
        conf_files: Iterable[str | os.PathLike] = (),
        overrides: Iterable[str] = (),
        include_site: bool = True,
    ) -> "TonyConf":
        """defaults -> files (in order) -> k=v overrides -> site conf."""
        conf = cls()
        for path in conf_files:
            conf.update_from_file(path)
        for kv in overrides:
            if "=" not in kv:
                raise ValueError(f"override must be key=value, got: {kv!r}")
            k, v = kv.split("=", 1)
            conf._data[k.strip()] = _coerce(v.strip())
        if include_site:
            site_dir = os.environ.get(SITE_CONF_ENV)
            if site_dir:
                site = Path(site_dir) / SITE_CONF_NAME
                if site.exists():
                    conf.update_from_file(site)
        return conf

    def update_from_file(self, path: str | os.PathLike) -> None:
        with open(path) as f:
            self._data.update(json.load(f))

    # --------------------------------------------------------------- access
    def get(self, key: str, default: Any = None) -> Any:
        return self._data.get(key, default)

    def __getitem__(self, key: str) -> Any:
        return self._data[key]

    def __contains__(self, key: str) -> bool:
        return key in self._data

    def get_int(self, key: str, default: int = 0) -> int:
        return int(self._data.get(key, default))

    def get_bool(self, key: str, default: bool = False) -> bool:
        v = self._data.get(key, default)
        if isinstance(v, str):
            return v.lower() == "true"
        return bool(v)

    def get_list(self, key: str, default: str = "") -> list[str]:
        raw = self._data.get(key, default)
        if isinstance(raw, (list, tuple)):
            # native JSON lists pass through verbatim — stringifying them
            # would comma-split "['a', 'b']" into quote-riddled garbage
            return [str(s).strip() for s in raw if str(s).strip()]
        raw = str(raw or "")
        return [s.strip() for s in re.split(r"[,\s]+", raw) if s.strip()]

    def set(self, key: str, value: Any) -> None:
        self._data[key] = value

    def as_dict(self) -> dict[str, Any]:
        return dict(self._data)

    # ---------------------------------------------------------------- roles
    def roles(self) -> list[str]:
        return keys.discover_roles(self._data)

    def role_specs(self) -> list[RoleSpec]:
        """Parse all roles into RoleSpecs with unique priorities.

        Mirrors Utils.parseContainerRequests (util/Utils.java:371-418):
        priorities are assigned uniquely per role so allocated capacity can be
        matched back to the role that asked for it.
        """
        specs = []
        for prio, role in enumerate(self.roles()):
            get = lambda t, d=None: self._data.get(keys.role_key(role, t), d)
            env_raw = get("env", "") or ""
            env = {}
            for kv in re.split(r"[,;]\s*", str(env_raw)):
                if "=" in kv:
                    k, v = kv.split("=", 1)
                    env[k.strip()] = v.strip()
            specs.append(
                RoleSpec(
                    name=role,
                    instances=int(get("instances", 0)),
                    memory_mb=int(get("memory-mb", 2048)),
                    vcores=int(get("vcores", 1)),
                    chips=int(get("chips", 0)),
                    command=str(get("command", "") or ""),
                    resources=[s for s in str(get("resources", "") or "").split(",") if s],
                    node_label=str(get("node-label", "") or ""),
                    depends_on=[
                        s.strip()
                        for s in str(get("depends-on", "") or "").split(",")
                        if s.strip()
                    ],
                    max_instances=int(get("max-instances", -1)),
                    max_restarts=int(get("max-restarts", 0)),
                    env=env,
                    priority=prio,
                    framework=str(get("framework", "") or ""),
                    priority_class=str(
                        get("priority-class", "") or "interactive").lower(),
                    quota=int(get("quota", -1)),
                )
            )
        return specs

    def untracked_roles(self) -> set[str]:
        return set(self.get_list(keys.APPLICATION_UNTRACKED_JOBTYPES))

    # ----------------------------------------------------------- validation
    def validate(self) -> None:
        """Instance/resource caps — reference TonyClient.java:796-866."""
        specs = self.role_specs()
        if not specs:
            raise ValueError("no roles configured (need at least one tony.<role>.instances)")
        total_inst = sum(s.instances for s in specs)
        if total_inst <= 0:
            raise ValueError("total instances must be > 0")
        max_inst = self.get_int(keys.TASK_MAX_TOTAL_INSTANCES, -1)
        if 0 <= max_inst < total_inst:
            raise ValueError(
                f"total instances {total_inst} exceeds {keys.TASK_MAX_TOTAL_INSTANCES}={max_inst}"
            )
        max_mem = self.get_int(keys.TASK_MAX_TOTAL_MEMORY_MB, -1)
        total_mem = sum(s.memory_mb * s.instances for s in specs)
        if 0 <= max_mem < total_mem:
            raise ValueError(
                f"total memory {total_mem}mb exceeds {keys.TASK_MAX_TOTAL_MEMORY_MB}={max_mem}"
            )
        max_chips = self.get_int(keys.TASK_MAX_TOTAL_CHIPS, -1)
        total_chips = sum(s.chips * s.instances for s in specs)
        if 0 <= max_chips < total_chips:
            raise ValueError(
                f"total chips {total_chips} exceeds {keys.TASK_MAX_TOTAL_CHIPS}={max_chips}"
            )
        for s in specs:
            if 0 <= s.max_instances < s.instances:
                raise ValueError(
                    f"role {s.name}: instances {s.instances} exceeds max-instances {s.max_instances}"
                )
        for s in specs:
            if s.priority_class not in ("interactive", "batch"):
                raise ValueError(
                    f"role {s.name}: priority-class must be 'interactive' "
                    f"or 'batch', got {s.priority_class!r}")
        if self.get_bool(keys.AUTOSCALE_ENABLED, False):
            role = str(self.get(keys.AUTOSCALE_ROLE, "") or "")
            if not role and len(specs) != 1:
                raise ValueError(
                    f"{keys.AUTOSCALE_ROLE} is required when the job has "
                    f"more than one role")
            if role and role not in {s.name for s in specs}:
                raise ValueError(
                    f"{keys.AUTOSCALE_ROLE}={role!r} names no configured "
                    "role")
        mode = str(self.get(keys.APPLICATION_DISTRIBUTED_MODE, "GANG")).upper()
        if mode not in ("GANG", "FCFS"):
            raise ValueError(f"distributed-mode must be GANG or FCFS, got {mode}")
        if self.get_bool(keys.DOCKER_ENABLED, False):
            # fail at submit, not per-executor at runtime
            for s in specs:
                if not (self.get(keys.docker_image_key(s.name))
                        or self.get(keys.DOCKER_IMAGE)):
                    raise ValueError(
                        f"{keys.DOCKER_ENABLED} is set but no image for role "
                        f"{s.name!r}: set {keys.DOCKER_IMAGE} or "
                        f"{keys.docker_image_key(s.name)}"
                    )

    # ------------------------------------------------------------- freezing
    def write_final(self, job_dir: str | os.PathLike) -> Path:
        """Freeze the resolved config — reference tony-final.xml write
        (TonyClient.java:232-315, ApplicationMaster.java:558-568)."""
        path = Path(job_dir) / FINAL_CONF_NAME
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "w") as f:
            json.dump(self._data, f, indent=2, sort_keys=True)
        return path

    @classmethod
    def from_final(cls, job_dir: str | os.PathLike) -> "TonyConf":
        with open(Path(job_dir) / FINAL_CONF_NAME) as f:
            return cls(json.load(f))
