"""Cluster capacity layer: provisioners + TPU topology discovery."""

from .provisioner import (
    ContainerHandle,
    LocalProvisioner,
    Provisioner,
    StaticHostProvisioner,
    create_provisioner,
)

__all__ = [
    "ContainerHandle",
    "LocalProvisioner",
    "Provisioner",
    "StaticHostProvisioner",
    "create_provisioner",
]
