"""TPU pod-slice provisioner.

The capacity model that replaces YARN in the rebuild (SURVEY.md §7): a TPU
slice is inherently gang-allocated — all hosts of a v5e-16/v5p-... slice
appear and disappear together — so per-container allocation races vanish and
the retry unit becomes "re-acquire the slice". One executor process runs per
TPU host (the reference's one-container-per-host shape,
TaskExecutor.java:188); `jax.distributed` then spans the slice's chips.

Host discovery options:
- tony.cluster.static-hosts: explicit host list (pre-created slice)
- tony.tpu.discover-command: a command printing one worker host per line
  (e.g. `gcloud compute tpus tpu-vm describe $NAME --format=...`), run at
  driver start — keeps cloud specifics out of the core.

Slice lifecycle (the RM capacity-*allocation* half — reference
TonyClient.submitApplication:317-353, container asks TaskScheduler.java:100
-102, async grants ApplicationMaster.java:1100-1119): when
tony.tpu.create-command is configured and discovery finds no (or a partial)
slice, the provisioner materializes one and polls discovery to READY; on
spot preemption `refresh()` deletes the carcass and re-creates, and
`teardown()` deletes only what this driver created. Without a create
command the provisioner is discovery-only (pre-created slices), exactly as
before.

Slice geometry (chips/host, hosts/slice) for common accelerator types is
tabulated so validation can reject role layouts that don't fit the slice.

Multislice (tony.tpu.num-slices > 1): every lifecycle/discover template is
instantiated once per slice with `{slice}` replaced by the slice index, so
each slice is its own cloud resource with its own create/await/recreate/
delete lifecycle; a preemption re-creates only the slice that died. The
provisioner knows which hosts belong to which slice and injects
TONY_SLICE_ID / TONY_NUM_SLICES / TONY_SLICE0_HOST into each launch — the
env contract the JAX runtime turns into cross-slice (MEGASCALE) transport.
Reference analogue: the RM granting containers across racks,
ApplicationMaster.java:1100-1119.
"""

from __future__ import annotations

import logging
import re
import subprocess
import time

from .. import constants as c
from ..conf import TonyConf, keys
from .provisioner import StaticHostProvisioner

log = logging.getLogger(__name__)

SLICE_PLACEHOLDER = "{slice}"


def slice_view(conf: TonyConf, slice_idx: int) -> TonyConf:
    """A conf copy with `{slice}` substituted into the lifecycle command
    templates — one cloud resource per slice index. With num-slices = 1 and
    no placeholder in the templates this is the identity."""
    sub = TonyConf(conf.as_dict())
    for key in (keys.TPU_DISCOVER_COMMAND, keys.TPU_CREATE_COMMAND,
                keys.TPU_DELETE_COMMAND):
        v = str(conf.get(key, "") or "")
        if v:
            sub.set(key, v.replace(SLICE_PLACEHOLDER, str(slice_idx)))
    return sub

# accelerator type -> (chips per host, total chips) for common slices
SLICE_GEOMETRY: dict[str, tuple[int, int]] = {
    "v4-8": (4, 4), "v4-16": (4, 8), "v4-32": (4, 16),
    "v5litepod-1": (1, 1), "v5litepod-4": (4, 4), "v5litepod-8": (8, 8),
    "v5litepod-16": (4, 16), "v5litepod-32": (4, 32), "v5litepod-64": (4, 64),
    "v5litepod-128": (4, 128), "v5litepod-256": (4, 256),
    "v5p-8": (4, 4), "v5p-16": (4, 8), "v5p-32": (4, 16),
    "v6e-1": (1, 1), "v6e-4": (4, 4), "v6e-8": (8, 8), "v6e-16": (4, 16),
    "v6e-32": (4, 32), "v6e-64": (4, 64), "v6e-128": (4, 128),
    "v6e-256": (4, 256),
}


# stderr fragments the major cloud CLIs emit for a genuinely absent
# resource (gcloud NOT_FOUND / "could not be found", generic 404s)
DEFAULT_NOT_FOUND_PATTERN = (
    r"(?i)not[_ ]?found|could not be found|does not exist|\b404\b"
)


class DiscoveryError(RuntimeError):
    """Host discovery failed. ``not_found=True`` means the cloud positively
    reported the slice absent (stderr matched tony.tpu.not-found-pattern, or
    a successful describe listed zero endpoints) — the only failure the
    lifecycle path may answer with delete+recreate. ``False`` is a
    transient/ambiguous failure (API 5xx, auth outage, describe timeout)
    that must never destroy possibly-healthy capacity."""

    def __init__(self, msg: str, not_found: bool = False):
        super().__init__(msg)
        self.not_found = not_found


def _not_found_re(conf: TonyConf) -> re.Pattern:
    """Compile tony.tpu.not-found-pattern eagerly so a malformed user regex
    is a config error at first use — not an re.error mid-await-READY that
    the lifecycle cleanup path would misread as a failed create."""
    pattern = str(
        conf.get(keys.TPU_NOT_FOUND_PATTERN, "") or ""
    ) or DEFAULT_NOT_FOUND_PATTERN
    try:
        return re.compile(pattern)
    except re.error as e:
        raise ValueError(
            f"invalid {keys.TPU_NOT_FOUND_PATTERN} regex {pattern!r}: {e}"
        ) from None


def slice_num_hosts(accelerator_type: str) -> int | None:
    geom = SLICE_GEOMETRY.get(accelerator_type)
    if geom is None:
        return None
    chips_per_host, total = geom
    return max(1, total // chips_per_host)


def discover_hosts(conf: TonyConf) -> list[str]:
    hosts = conf.get_list(keys.CLUSTER_STATIC_HOSTS)
    if hosts:
        return hosts
    cmd = str(conf.get(keys.TPU_DISCOVER_COMMAND, "") or "")
    if cmd:
        out = subprocess.run(
            cmd, shell=True, capture_output=True, text=True, timeout=120
        )
        if out.returncode != 0:
            stderr = out.stderr.strip()
            raise DiscoveryError(
                f"tpu host discovery failed: {stderr}",
                not_found=bool(_not_found_re(conf).search(stderr)),
            )
        hosts = [h.strip() for h in out.stdout.splitlines() if h.strip()]
        if not hosts:
            # the describe SUCCEEDED and listed zero endpoints: positive
            # absence, not a flake
            raise DiscoveryError(
                "tpu host discovery returned no hosts", not_found=True
            )
    if not hosts:
        raise ValueError(
            "no TPU hosts: set tony.cluster.static-hosts or "
            + keys.TPU_DISCOVER_COMMAND
        )
    return hosts


def create_slice(conf: TonyConf) -> None:
    """Run the configured create command (the submitApplication analogue).
    Raises on nonzero exit — a create that the cloud rejects is a hard
    submit error, not something to poll through. The subprocess deadline is
    the configured create timeout, so a blocking (non --async) create is
    given the same budget as the await-READY poll."""
    cmd = str(conf.get(keys.TPU_CREATE_COMMAND, "") or "")
    if not cmd:
        raise ValueError(f"{keys.TPU_CREATE_COMMAND} is not set")
    log.info("creating tpu slice: %s", cmd)
    out = subprocess.run(
        cmd, shell=True, capture_output=True, text=True,
        timeout=max(60.0, float(conf.get(keys.TPU_CREATE_TIMEOUT_S, 1800))),
    )
    if out.returncode != 0:
        raise RuntimeError(f"tpu slice create failed: {out.stderr.strip()}")


def delete_slice(conf: TonyConf) -> bool:
    """Run the configured delete command. Best-effort by design (the
    carcass of a preempted slice may already be gone, and teardown must
    not turn a finished job into a failed one): returns False and logs
    instead of raising."""
    cmd = str(conf.get(keys.TPU_DELETE_COMMAND, "") or "")
    if not cmd:
        return False
    log.info("deleting tpu slice: %s", cmd)
    try:
        out = subprocess.run(
            cmd, shell=True, capture_output=True, text=True, timeout=1800
        )
    except Exception:
        log.exception("tpu slice delete errored")
        return False
    if out.returncode != 0:
        log.warning("tpu slice delete failed: %s", out.stderr.strip())
        return False
    return True


def await_slice_ready(conf: TonyConf, expected_hosts: int | None) -> list[str]:
    """Poll discovery until the slice reports its full host complement —
    the await-READY phase of allocation (the analogue of waiting for the
    RM's async container grants). Discovery failures while the slice is
    still materializing (cloud CLIs error on a not-yet-existing resource)
    are part of the normal wait, not errors.

    Without an accelerator type there is no expected host count, so a
    mid-creation describe that lists only some endpoints cannot be told
    from READY by size; the fallback heuristic is to require the host list
    to be identical across tony.tpu.ready-stable-polls consecutive polls
    (default 3) before declaring READY — a cloud that stalls on a partial
    endpoint list for that long still gets the gang packed onto a partial
    slice, so set tony.tpu.accelerator-type for an exact check."""
    timeout_s = float(conf.get(keys.TPU_CREATE_TIMEOUT_S, 1800))
    poll_s = float(conf.get(keys.TPU_CREATE_POLL_S, 10))
    stable_needed = max(2, int(conf.get(keys.TPU_READY_STABLE_POLLS, 3)))
    if expected_hosts is None:
        log.warning(
            "no %s: declaring READY after %d identical host lists — a "
            "stalled partial endpoint list can fool this; set the "
            "accelerator type for an exact host-count check",
            keys.TPU_ACCELERATOR_TYPE, stable_needed,
        )
    deadline = time.monotonic() + timeout_s
    last_state = "no hosts yet"
    last_hosts: list[str] = []
    stable_count = 0
    while time.monotonic() < deadline:
        try:
            hosts = discover_hosts(conf)
        except (RuntimeError, ValueError, subprocess.SubprocessError) as e:
            # SubprocessError: a describe that hangs/timeouts mid-allocation
            # is part of the normal wait too, not a reason to abort
            last_state = str(e)
            last_hosts = []
            stable_count = 0
        else:
            if expected_hosts is not None:
                if len(hosts) == expected_hosts:
                    return hosts
                last_state = f"{len(hosts)}/{expected_hosts} hosts"
            elif hosts == last_hosts:
                stable_count += 1
                if stable_count >= stable_needed - 1:
                    return hosts
                last_state = (
                    f"{len(hosts)} hosts (stable {stable_count + 1}/"
                    f"{stable_needed} polls)"
                )
            else:
                last_state = f"{len(hosts)} hosts (awaiting a stable list)"
                last_hosts = hosts
                stable_count = 0
        time.sleep(poll_s)
    raise TimeoutError(
        f"tpu slice not READY after {timeout_s:.0f}s (last: {last_state})"
    )


class TpuPodProvisioner(StaticHostProvisioner):
    """Gang launch over the hosts of one slice, with optional ownership of
    the slice's lifecycle (create / await-READY / recreate / delete)."""

    def __init__(self, conf: TonyConf, on_constructing=None):
        self._conf = conf
        self.accelerator_type = str(
            conf.get(keys.TPU_ACCELERATOR_TYPE, "") or ""
        )
        self.num_slices = max(1, conf.get_int(keys.TPU_NUM_SLICES, 1))
        # slice indices THIS provisioner materialized: teardown only deletes
        # driver-created capacity, never a user's pre-created slice
        self._created_slices: set[int] = set()
        self._slice_hosts: list[list[str]] = []
        _not_found_re(conf)  # reject a malformed pattern before any I/O
        if on_constructing is not None:
            # expose the instance BEFORE acquisition: teardown() depends
            # only on (_created_slices, _conf), both set, so a signal
            # handler can release slices created during the (possibly
            # minutes-long) await-READY polls below. stop_all/launch are
            # NOT safe yet.
            on_constructing(self)
        hosts = self._acquire()
        template = str(
            conf.get(keys.CLUSTER_LAUNCH_TEMPLATE, "") or ""
        ) or None
        super().__init__(hosts, launch_template=template)
        log.info(
            "tpu capacity: %d hosts / %d slice(s) (%s)%s", len(hosts),
            self.num_slices, self.accelerator_type or "unknown type",
            f" [driver-created: {sorted(self._created_slices)}]"
            if self._created_slices else "",
        )

    @property
    def created(self) -> bool:
        """True once this provisioner materialized ANY slice."""
        return bool(self._created_slices)

    @property
    def _expected_hosts(self) -> int | None:
        return (slice_num_hosts(self.accelerator_type)
                if self.accelerator_type else None)

    def _host_env(self, host_index: int, host: str) -> dict[str, str]:
        """The multislice env contract: which slice this task's host sits
        on, the slice count, and slice 0's first host (the cross-slice
        rendezvous point the JAX adapter feeds to MEGASCALE transport)."""
        if self.num_slices <= 1:
            return {}
        sid, seen = 0, 0
        for i, sh in enumerate(self._slice_hosts):
            if host_index < seen + len(sh):
                sid = i
                break
            seen += len(sh)
        return {
            c.ENV_SLICE_ID: str(sid),
            c.ENV_NUM_SLICES: str(self.num_slices),
            c.ENV_SLICE0_HOST: self._slice_hosts[0][0],
        }

    def _acquire(self, during_refresh: bool = False) -> list[str]:
        """Discover every slice; materialize the absent ones (when a create
        command is configured) — the allocation half of the reference RM
        (submitApplication:317-353 + async grants). Shared by __init__ and
        refresh() so the two paths cannot drift. Per-slice: a preemption
        that killed slice 2 re-creates slice 2 only."""
        create_cmd = str(self._conf.get(keys.TPU_CREATE_COMMAND, "") or "")
        if create_cmd and not (
            str(self._conf.get(keys.TPU_DISCOVER_COMMAND, "") or "")
            or self._conf.get_list(keys.CLUSTER_STATIC_HOSTS)
        ):
            # fail the misconfiguration in seconds — before the retry loop
            # and the create path burn minutes against a discovery that can
            # never succeed
            raise ValueError(
                f"{keys.TPU_CREATE_COMMAND} is set but there is no way to "
                f"await READY: configure {keys.TPU_DISCOVER_COMMAND} (or "
                f"{keys.CLUSTER_STATIC_HOSTS})"
            )
        if self.num_slices > 1:
            if not self._conf.get(keys.TPU_DISCOVER_COMMAND):
                raise ValueError(
                    f"{keys.TPU_NUM_SLICES}={self.num_slices} needs "
                    f"per-slice discovery: set {keys.TPU_DISCOVER_COMMAND} "
                    "(static host lists carry no slice boundaries)"
                )
            # every configured template must be {slice}-parameterized:
            # without the placeholder slice_view() is the identity and all
            # N "slices" would operate on ONE cloud resource — double-
            # booked hosts, conflicting slice ids, and a slice-1 refresh
            # deleting the resource slice 0 is running on
            for key in (keys.TPU_DISCOVER_COMMAND, keys.TPU_CREATE_COMMAND,
                        keys.TPU_DELETE_COMMAND):
                v = str(self._conf.get(key, "") or "")
                if v and SLICE_PLACEHOLDER not in v:
                    raise ValueError(
                        f"{keys.TPU_NUM_SLICES}={self.num_slices} but {key} "
                        f"has no {SLICE_PLACEHOLDER} placeholder — each "
                        "slice must be its own cloud resource"
                    )
        slice_hosts = [
            self._acquire_slice(s, during_refresh)
            for s in range(self.num_slices)
        ]
        self._slice_hosts = slice_hosts
        return [h for sh in slice_hosts for h in sh]

    def _acquire_slice(self, s: int, during_refresh: bool) -> list[str]:
        """Acquire ONE slice (index `s`; templates instantiated via
        slice_view).

        Declaring a slice gone triggers delete+create, so a single
        transient discovery flake (API 5xx, auth hiccup, describe timeout)
        must not destroy healthy — possibly user-pre-created — capacity:
        discovery is retried tony.tpu.discover-retries times, and only
        positive evidence (a NOT_FOUND stderr match, or a successful
        describe listing the wrong host count) may engage the lifecycle
        path."""
        sconf = slice_view(self._conf, s)
        create_cmd = str(sconf.get(keys.TPU_CREATE_COMMAND, "") or "")
        expected = self._expected_hosts
        attempts = max(1, int(sconf.get(keys.TPU_DISCOVER_RETRIES, 3)))
        poll_s = float(sconf.get(keys.TPU_CREATE_POLL_S, 10))
        err: Exception | None = None
        confirmed_gone = False
        for attempt in range(attempts):
            if attempt:
                time.sleep(poll_s)
            try:
                hosts = discover_hosts(sconf)
                if expected is not None and len(hosts) != expected:
                    confirmed_gone = True  # successful describe, wrong size
                    if during_refresh:
                        raise ValueError(
                            f"slice {s} refresh found {len(hosts)} hosts, "
                            f"accelerator {self.accelerator_type} has "
                            f"{expected} (slice still recreating?)"
                        )
                    raise ValueError(
                        f"accelerator {self.accelerator_type} has {expected} "
                        f"hosts, slice {s} got {len(hosts)}"
                    )
                return hosts
            except (RuntimeError, ValueError,
                    subprocess.SubprocessError) as e:
                err = e
                confirmed_gone = confirmed_gone or getattr(
                    e, "not_found", False
                )
                log.info("slice %d discovery attempt %d/%d: %s",
                         s, attempt + 1, attempts, e)
        assert err is not None
        if not create_cmd:
            raise err  # discovery-only mode: absent slice is the user's error
        if not confirmed_gone:
            raise RuntimeError(
                f"slice {s} discovery failed {attempts}x without the cloud "
                f"confirming the slice absent (set "
                f"{keys.TPU_NOT_FOUND_PATTERN} if your CLI's not-found "
                f"message is unusual); refusing to delete+recreate "
                f"capacity that may be healthy: {err}"
            ) from err
        log.info("slice %d confirmed absent or partial; creating", s)
        # even a failed create may leave capacity behind
        self._created_slices.add(s)
        try:
            # clear any remnant under the same name first (a preemption
            # carcass or half-created slice makes the create fail "exists")
            delete_slice(sconf)
            create_slice(sconf)
            return await_slice_ready(sconf, expected)
        except Exception:
            # a created-but-never-READY slice is billable capacity nothing
            # tracks once this raise aborts the driver — delete it now
            if delete_slice(sconf):
                self._created_slices.discard(s)
            raise

    def refresh(self) -> None:
        """Re-acquire every slice before a retry attempt (the "re-acquire
        the slice, not a container" retry unit, SURVEY.md §7). A preempted
        spot slice comes back with NEW host addresses, so every retry must
        re-discover; a slice discovery shows gone (or partial) is deleted
        and re-created — only that slice. Raising keeps the previous host
        list (the driver logs and retries with it)."""
        hosts = self._acquire(during_refresh=True)
        if hosts != self.hosts:
            log.info("tpu capacity refresh: hosts %s -> %s",
                     self.hosts, hosts)
        self.hosts = hosts

    def teardown(self) -> None:
        """Delete every driver-created slice at job end (symmetric with
        YARN releasing containers the RM granted; a user's pre-created
        slice outlives the job)."""
        for s in sorted(self._created_slices):
            delete_slice(slice_view(self._conf, s))

    def validate_layout(self, conf: TonyConf) -> None:
        """Every TPU-holding task needs its own host (libtpu is exclusive
        per host — the analogue of the reference's GPU isolation)."""
        total = sum(
            s.instances for s in conf.role_specs() if s.chips > 0
        )
        if total > len(self.hosts):
            raise ValueError(
                f"{total} TPU tasks > {len(self.hosts)} slice hosts"
            )
