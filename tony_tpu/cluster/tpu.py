"""TPU pod-slice provisioner.

The capacity model that replaces YARN in the rebuild (SURVEY.md §7): a TPU
slice is inherently gang-allocated — all hosts of a v5e-16/v5p-... slice
appear and disappear together — so per-container allocation races vanish and
the retry unit becomes "re-acquire the slice". One executor process runs per
TPU host (the reference's one-container-per-host shape,
TaskExecutor.java:188); `jax.distributed` then spans the slice's chips.

Host discovery options:
- tony.cluster.static-hosts: explicit host list (pre-created slice)
- tony.tpu.discover-command: a command printing one worker host per line
  (e.g. `gcloud compute tpus tpu-vm describe $NAME --format=...`), run at
  driver start — keeps cloud specifics out of the core.

Slice geometry (chips/host, hosts/slice) for common accelerator types is
tabulated so validation can reject role layouts that don't fit the slice.
"""

from __future__ import annotations

import logging
import subprocess

from ..conf import TonyConf, keys
from .provisioner import StaticHostProvisioner

log = logging.getLogger(__name__)

# accelerator type -> (chips per host, total chips) for common slices
SLICE_GEOMETRY: dict[str, tuple[int, int]] = {
    "v4-8": (4, 4), "v4-16": (4, 8), "v4-32": (4, 16),
    "v5litepod-1": (1, 1), "v5litepod-4": (4, 4), "v5litepod-8": (8, 8),
    "v5litepod-16": (4, 16), "v5litepod-32": (4, 32), "v5litepod-64": (4, 64),
    "v5litepod-128": (4, 128), "v5litepod-256": (4, 256),
    "v5p-8": (4, 4), "v5p-16": (4, 8), "v5p-32": (4, 16),
    "v6e-1": (1, 1), "v6e-4": (4, 4), "v6e-8": (8, 8), "v6e-16": (4, 16),
    "v6e-32": (4, 32), "v6e-64": (4, 64), "v6e-128": (4, 128),
    "v6e-256": (4, 256),
}


def slice_num_hosts(accelerator_type: str) -> int | None:
    geom = SLICE_GEOMETRY.get(accelerator_type)
    if geom is None:
        return None
    chips_per_host, total = geom
    return max(1, total // chips_per_host)


def discover_hosts(conf: TonyConf) -> list[str]:
    hosts = conf.get_list(keys.CLUSTER_STATIC_HOSTS)
    if hosts:
        return hosts
    cmd = str(conf.get(keys.TPU_DISCOVER_COMMAND, "") or "")
    if cmd:
        out = subprocess.run(
            cmd, shell=True, capture_output=True, text=True, timeout=120
        )
        if out.returncode != 0:
            raise RuntimeError(f"tpu host discovery failed: {out.stderr.strip()}")
        hosts = [h.strip() for h in out.stdout.splitlines() if h.strip()]
    if not hosts:
        raise ValueError(
            "no TPU hosts: set tony.cluster.static-hosts or "
            + keys.TPU_DISCOVER_COMMAND
        )
    return hosts


class TpuPodProvisioner(StaticHostProvisioner):
    """Gang launch over the hosts of one slice."""

    def __init__(self, conf: TonyConf):
        hosts = discover_hosts(conf)
        accel = str(conf.get(keys.TPU_ACCELERATOR_TYPE, "") or "")
        expected = slice_num_hosts(accel) if accel else None
        if expected is not None and len(hosts) != expected:
            raise ValueError(
                f"accelerator {accel} has {expected} hosts, got {len(hosts)}"
            )
        super().__init__(hosts)
        self._conf = conf
        self.accelerator_type = accel
        log.info("tpu slice: %d hosts (%s)", len(hosts), accel or "unknown type")

    def refresh(self) -> None:
        """Re-run host discovery before a retry attempt. A preempted spot
        slice comes back with NEW host addresses — without re-discovery
        every retry would SSH the dead slice (the "re-acquire the slice,
        not a container" retry unit, SURVEY.md §7). No-op for static host
        lists (discover_hosts returns those first).

        Validates the host count against the accelerator geometry exactly
        like __init__ — a slice mid-recreation can report a partial host
        list, and packing tasks onto it would break the one-TPU-task-per-
        host invariant. Raising keeps the previous host list (the driver
        logs and retries with it)."""
        hosts = discover_hosts(self._conf)
        expected = (slice_num_hosts(self.accelerator_type)
                    if self.accelerator_type else None)
        if expected is not None and len(hosts) != expected:
            raise ValueError(
                f"slice refresh found {len(hosts)} hosts, accelerator "
                f"{self.accelerator_type} has {expected} (slice still "
                "recreating?)"
            )
        if hosts != self.hosts:
            log.info("tpu slice refresh: hosts %s -> %s", self.hosts, hosts)
        self.hosts = hosts

    def validate_layout(self, conf: TonyConf) -> None:
        """Every TPU-holding task needs its own host (libtpu is exclusive
        per host — the analogue of the reference's GPU isolation)."""
        total = sum(
            s.instances for s in conf.role_specs() if s.chips > 0
        )
        if total > len(self.hosts):
            raise ValueError(
                f"{total} TPU tasks > {len(self.hosts)} slice hosts"
            )
