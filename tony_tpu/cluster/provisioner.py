"""Capacity provisioners: how the driver obtains processes on hosts.

The reference's equivalent layer is YARN: the AM asks the RM for containers
(TaskScheduler.java:100-102) and launches them through NodeManagers
(ApplicationMaster.ContainerLauncher:1158-1227). A TPU pod slice is inherently
gang-allocated — all hosts of a slice appear at once — which removes per
-container allocation races but makes "re-acquire the whole slice" the retry
unit (SURVEY.md §7 hard parts).

Provisioners implemented:
- LocalProvisioner: subprocesses on this host — the mini-cluster backend used
  by tests and `tony-tpu local` (reference tony-mini MiniCluster role).
- StaticHostProvisioner: a fixed host list (one TPU host per worker), launch
  via a configurable command template (ssh/agent); models a pre-created TPU
  pod slice where host i runs task i.
"""

from __future__ import annotations

import logging
import os
import shlex
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable

from ..conf import RoleSpec, TonyConf, keys

log = logging.getLogger(__name__)


@dataclass
class ContainerHandle:
    """An allocated unit of capacity running one executor."""

    container_id: str
    host: str
    role: str
    index: int
    process: subprocess.Popen | None = None
    extra: dict = field(default_factory=dict)


class Provisioner:
    """SPI. `on_completion(handle, exit_code)` is invoked from a watcher
    thread when a container exits — the analogue of the RM completion
    callback (ApplicationMaster.processFinishedContainer:1238-1274)."""

    def __init__(self) -> None:
        self.on_completion: Callable[[ContainerHandle, int], None] | None = None

    def launch(
        self, spec: RoleSpec, index: int, env: dict[str, str], log_dir: Path
    ) -> ContainerHandle:
        raise NotImplementedError

    def stop_container(self, handle: ContainerHandle) -> None:
        raise NotImplementedError

    def kill_container(self, handle: ContainerHandle) -> None:
        """Hard-kill one container with NO drain grace — the driver's
        chaos harness and tests use it to model abrupt host death
        (SIGKILL), unlike stop_container's SIGTERM-then-escalate.
        Default falls back to the graceful stop for provisioners without
        a harder hammer."""
        self.stop_container(handle)

    def stop_all(self) -> None:
        raise NotImplementedError

    def teardown(self) -> None:
        """Release provisioner-OWNED capacity (e.g. a TPU slice this
        provisioner created) at end of job. Default: nothing is owned.
        Must be safe to call at any point after __init__ begins — the
        driver's signal path may invoke it mid-construction."""


class LocalProvisioner(Provisioner):
    """Executors as local subprocesses; per-task stdout/stderr files mirror
    YARN container log dirs."""

    # how long stop_container waits for the SIGTERM'd executor before
    # escalating to a group SIGKILL. NOTE: for driver-initiated drains
    # (rolls, elastic resize) this also bounds the EFFECTIVE preemption
    # grace — a child still checkpointing when the window closes is
    # SIGKILLed with its executor (docs/training-robustness.md) — so a
    # deployment raising tony.task.preempt-grace-ms past this should
    # raise it too.
    stop_wait_s = 5.0

    def __init__(self) -> None:
        super().__init__()
        self._handles: dict[str, ContainerHandle] = {}
        self._lock = threading.Lock()
        self._next_id = 0

    def launch(
        self, spec: RoleSpec, index: int, env: dict[str, str], log_dir: Path
    ) -> ContainerHandle:
        with self._lock:
            cid = f"container_{self._next_id:06d}"
            self._next_id += 1
        log_dir.mkdir(parents=True, exist_ok=True)
        stdout_path = log_dir / f"{spec.name}_{index}.stdout"
        stdout = open(stdout_path, "ab")
        stderr = open(log_dir / f"{spec.name}_{index}.stderr", "ab")
        full_env = {**os.environ, **env}
        # -S skips site hooks (this environment's sitecustomize imports jax,
        # ~1.8s); the executor agent is pure stdlib, and the user process it
        # forks gets a normal interpreter
        proc = subprocess.Popen(
            [sys.executable, "-S", "-m", "tony_tpu.executor"],
            env=full_env,
            stdout=stdout,
            stderr=stderr,
            start_new_session=True,  # own process group => clean kill of user children
        )
        handle = ContainerHandle(
            container_id=cid, host="127.0.0.1", role=spec.name, index=index, process=proc
        )
        # the log location the driver should advertise for this task — owned
        # by the provisioner that opened the file, not re-derived elsewhere
        handle.extra["log_path"] = str(stdout_path)
        with self._lock:
            self._handles[cid] = handle
        threading.Thread(
            target=self._watch, args=(handle, stdout, stderr),
            name=f"watch-{cid}", daemon=True,
        ).start()
        return handle

    def _watch(self, handle: ContainerHandle, *files) -> None:
        code = handle.process.wait()
        for f in files:
            try:
                f.close()
            except Exception:
                pass
        cb = self.on_completion
        if cb is not None:
            try:
                cb(handle, code)
            except Exception:
                log.exception("completion callback failed for %s", handle.container_id)

    def adopt_container(self, container_id: str, host: str, role: str,
                        index: int, pid: int,
                        log_path: str = "") -> ContainerHandle:
        """Re-adopt a PREVIOUS driver incarnation's executor by pid
        (control-plane recovery, events/driver_journal.py): a
        Popen-less handle whose process this provisioner never spawned.
        Deliberately no watcher thread — a non-child pid has no
        waitable exit status; the re-adopted task's authoritative
        completion is its executor's own register_execution_result (the
        recovered driver routes it through the container path), and a
        silently dead orphan is detected by heartbeat expiry. Signals
        still work: the executor runs in its own session, so its pid is
        its process-group id."""
        handle = ContainerHandle(
            container_id=container_id, host=host, role=role, index=index,
            process=None,
            extra={"adopted": True, "pid": int(pid), "log_path": log_path},
        )
        with self._lock:
            self._handles[container_id] = handle
        return handle

    @staticmethod
    def _group_pid(handle: ContainerHandle) -> int:
        """The process-group id to signal: the spawned child's pid, or a
        re-adopted handle's journaled pid (0 = nothing to signal). Both
        kinds were started with start_new_session, so pid == pgid."""
        if handle.process is not None:
            return handle.process.pid if handle.process.poll() is None else 0
        pid = handle.extra.get("pid", 0)
        if not isinstance(pid, int) or pid <= 0:
            return 0
        from ..warmpool import _pid_alive

        return pid if _pid_alive(pid) else 0

    def stop_container(self, handle: ContainerHandle) -> None:
        pid = self._group_pid(handle)
        if not pid:
            return
        try:
            os.killpg(pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            return
        if handle.process is not None:
            try:
                handle.process.wait(timeout=self.stop_wait_s)
                return
            except subprocess.TimeoutExpired:
                pass
        else:
            # adopted (non-child) pid: poll liveness for the same grace
            from ..warmpool import _pid_alive

            deadline = time.monotonic() + self.stop_wait_s
            while time.monotonic() < deadline:
                if not _pid_alive(pid):
                    return
                time.sleep(0.05)
        try:
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def kill_container(self, handle: ContainerHandle) -> None:
        """SIGKILL the whole process group immediately (abrupt host
        death for the chaos harness); the watcher thread reports the
        completion like any crash."""
        pid = self._group_pid(handle)
        if not pid:
            return
        try:
            os.killpg(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def stop_all(self) -> None:
        with self._lock:
            handles = list(self._handles.values())
        for h in handles:
            self.stop_container(h)


class StaticHostProvisioner(Provisioner):
    """Fixed host list; each task launched by substituting into a command
    template (default: ssh). Round-robins tasks over hosts, so a v5e-16
    (4 hosts) with tony.worker.instances=4 puts one executor per TPU host."""

    def __init__(self, hosts: list[str], launch_template: str | None = None) -> None:
        # _local must exist before super().__init__ touches the
        # on_completion property this class redirects to it
        self._local = LocalProvisioner()
        super().__init__()
        if not hosts:
            raise ValueError("StaticHostProvisioner needs at least one host")
        self.hosts = hosts
        self.launch_template = launch_template or (
            "ssh -o BatchMode=yes {host} {env} " + sys.executable + " -m tony_tpu.executor"
        )
        self._count = 0
        self._lock = threading.Lock()

    @property
    def on_completion(self):  # delegate watcher callback to inner provisioner
        return self._local.on_completion

    @on_completion.setter
    def on_completion(self, cb):
        self._local.on_completion = cb

    def _host_env(self, host_index: int, host: str) -> dict[str, str]:
        """Extra env derived from WHERE the task landed — capacity topology
        only the provisioner knows (e.g. the multislice contract vars).
        Keyed by host index, not name: stub clouds may report identical
        names across slices."""
        return {}

    def launch(
        self, spec: RoleSpec, index: int, env: dict[str, str], log_dir: Path
    ) -> ContainerHandle:
        with self._lock:
            host_index = self._count % len(self.hosts)
            host = self.hosts[host_index]
            self._count += 1
        extra = self._host_env(host_index, host)
        if extra:
            env = {**env, **extra}
        env_str = " ".join(f"{k}={shlex.quote(str(v))}" for k, v in env.items())
        # token replace, not str.format: the template is arbitrary shell
        # where literal braces (${VAR}, awk '{...}') are ordinary syntax
        cmd = self.launch_template.replace("{host}", host).replace("{env}", env_str)
        log_dir.mkdir(parents=True, exist_ok=True)
        stdout_path = log_dir / f"{spec.name}_{index}.stdout"
        stdout = open(stdout_path, "ab")
        stderr = open(log_dir / f"{spec.name}_{index}.stderr", "ab")
        proc = subprocess.Popen(
            cmd, shell=True, stdout=stdout, stderr=stderr, start_new_session=True
        )
        handle = ContainerHandle(
            container_id=f"static_{host}_{spec.name}_{index}",
            host=host, role=spec.name, index=index, process=proc,
        )
        handle.extra["log_path"] = str(stdout_path)
        # register with the inner provisioner so stop_all() reaps the ssh
        # client processes (sshd then tears down the remote session, taking
        # the remote executor with it)
        with self._local._lock:
            self._local._handles[handle.container_id] = handle
        threading.Thread(
            target=self._local._watch, args=(handle, stdout, stderr), daemon=True
        ).start()
        return handle

    def stop_container(self, handle: ContainerHandle) -> None:
        self._local.stop_container(handle)

    def kill_container(self, handle: ContainerHandle) -> None:
        # kills the local ssh client; sshd tears down the remote session
        self._local.kill_container(handle)

    def stop_all(self) -> None:
        self._local.stop_all()


def create_provisioner(conf: TonyConf, on_constructing=None) -> Provisioner:
    """`on_constructing(prov)` is invoked with the instance BEFORE any
    capacity acquisition runs (for lifecycle provisioners), so a signal
    handler can reach `prov.teardown()` even when the process dies while
    the slice is still materializing — the await-READY poll can last
    minutes and is the likeliest window for a user kill."""
    kind = str(conf.get(keys.CLUSTER_PROVISIONER, "local")).lower()
    if kind == "local":
        return LocalProvisioner()
    if kind == "static":
        hosts = conf.get_list(keys.CLUSTER_STATIC_HOSTS)
        template = str(conf.get(keys.CLUSTER_LAUNCH_TEMPLATE, "") or "") or None
        return StaticHostProvisioner(hosts, launch_template=template)
    if kind in ("tpu-pod", "tpu"):
        from .tpu import TpuPodProvisioner

        prov = TpuPodProvisioner(conf, on_constructing=on_constructing)
        try:
            prov.validate_layout(conf)
        except Exception:
            # a layout rejection aborts the driver before stop() ever runs;
            # release any slice the provisioner just created
            prov.teardown()
            raise
        return prov
    raise ValueError(f"unknown provisioner: {kind}")
