"""Blockwise (logits-free) cross entropy for large vocabularies.

The last matmul of an LM — ``hidden @ unembed`` — produces a [B*L, V] f32
logits tensor that usually dwarfs every activation in the model: at
B*L=32k, V=256k that is 32GB, and XLA autodiff keeps it (plus the softmax)
alive for the backward. This op fuses the unembed matmul with the softmax
cross entropy by streaming the vocabulary in blocks under ``lax.scan``:

- forward: running (max, sumexp) over vocab blocks — the classic online
  logsumexp — plus an in-block gather of each row's target logit. Peak
  live memory is [N, block_v] instead of [N, V].
- backward (custom VJP): one more sweep over vocab blocks recomputing the
  block logits from the saved (hidden, unembed, lse) residuals;
  ``ds = g * (softmax_block - onehot_block)`` feeds both dx (accumulated)
  and dW (written block-by-block into a single [D, V] carry). Nothing of
  size [N, V] ever exists, and no extra copy of the unembed is made:
  ragged vocabularies are handled by clamping the last block's start and
  masking the overlapped columns, not by padding the matrix.

Every block op is a large dense matmul -> MXU-friendly; block_v defaults to
a lane-aligned 2048. This is an XLA-level fusion (scan + matmuls), not a
Pallas kernel: the matmuls already saturate the MXU and XLA fuses the
elementwise tail into them, so a hand kernel would only re-derive the same
schedule.

Sharding note: the blockwise sweep slices the vocab axis with a traced
start index, which forces GSPMD to gather a vocab-sharded (tensor-parallel)
unembed. The model-side dispatch (models/transformer.py token_nll) therefore
keeps the dense sharded path whenever the mesh has a tensor axis; blockwise
is for the DP/FSDP/SP regimes where the unembed is replicated or
fully-sharded-then-gathered anyway.

No reference counterpart: TonY has no compute layer (SURVEY.md §2.3); this
is part of the TPU-native capability layer.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30
DEFAULT_BLOCK_V = 2048


def _num_blocks(v: int, block_v: int) -> int:
    return -(-v // block_v)


def _block_cols(x, w, j, block_v, v):
    """Logits for vocab block j in f32 without copying/padding w: the last
    block's start is clamped to v - block_v, and columns already covered by
    the previous block are masked to NEG_INF. Returns (logits [N, BV],
    start, cols [N, BV] global column ids, owned mask or None)."""
    lo = j * block_v
    start = jnp.minimum(lo, v - block_v)
    wj = lax.dynamic_slice_in_dim(w, start, block_v, axis=1)
    logits = jnp.dot(x, wj, preferred_element_type=jnp.float32)
    cols = start[None, None] + lax.broadcasted_iota(jnp.int32, logits.shape, 1)
    if v % block_v != 0:
        owned = cols >= lo
        logits = jnp.where(owned, logits, NEG_INF)
    else:
        owned = None
    return logits, start, cols, owned


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def blockwise_cross_entropy(x, w, targets, block_v=DEFAULT_BLOCK_V):
    """Per-row softmax cross entropy of ``x @ w`` against ``targets``
    without materializing the [N, V] logits.

    x: [N, D] hidden states (any float dtype; accumulation in f32)
    w: [D, V] unembedding matrix
    targets: [N] int — caller handles padding rows (mask the returned nll)
    -> nll [N] f32
    """
    nll, _ = _ce_fwd_pass(x, w, targets, block_v)
    return nll


def _ce_fwd_pass(x, w, targets, block_v):
    v = w.shape[1]
    block_v = min(block_v, v)
    nb = _num_blocks(v, block_v)
    n = x.shape[0]

    def body(carry, j):
        m, l, tl = carry
        logits, start, _, _ = _block_cols(x, w, j, block_v, v)   # [N, BV]
        bm = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, bm)
        l_new = l * jnp.exp(m - m_new) + jnp.sum(
            jnp.exp(logits - m_new[:, None]), axis=-1
        )
        # in-block target gather: rows whose target this block owns
        lo = j * block_v
        in_blk = (targets >= lo) & (targets < lo + block_v)
        idx = jnp.clip(targets - start, 0, block_v - 1)
        row_logit = jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0]
        tl = jnp.where(in_blk, row_logit, tl)
        return (m_new, l_new, tl), None

    m0 = jnp.full((n,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((n,), jnp.float32)
    tl0 = jnp.zeros((n,), jnp.float32)
    (m, l, tl), _ = lax.scan(body, (m0, l0, tl0), jnp.arange(nb))
    lse = m + jnp.log(jnp.maximum(l, 1e-37))
    return lse - tl, lse


def _ce_vjp_fwd(x, w, targets, block_v):
    nll, lse = _ce_fwd_pass(x, w, targets, block_v)
    return nll, (x, w, targets, lse)


def _ce_vjp_bwd(block_v, res, g):
    x, w, targets, lse = res
    v = w.shape[1]
    block_v = min(block_v, v)
    nb = _num_blocks(v, block_v)
    gf = g.astype(jnp.float32)
    xf32t = x.astype(jnp.float32).T

    def body(carry, j):
        dx, dw = carry
        logits, start, cols, owned = _block_cols(x, w, j, block_v, v)
        p = jnp.exp(logits - lse[:, None])            # masked cols: exp->0
        onehot = cols == targets[:, None]
        if owned is not None:
            onehot &= owned                           # target owned elsewhere
        ds = gf[:, None] * (p - onehot)               # [N, BV] f32, 0 in overlap
        wj = lax.dynamic_slice_in_dim(w, start, block_v, axis=1)
        dx = dx + jnp.dot(
            ds, wj.T.astype(jnp.float32), preferred_element_type=jnp.float32
        )
        dwj = jnp.dot(xf32t, ds, preferred_element_type=jnp.float32)  # [D, BV]
        # read-modify-write the block into the single [D, V] accumulator;
        # overlapped columns add exact zeros (ds masked), so no double count
        cur = lax.dynamic_slice_in_dim(dw, start, block_v, axis=1)
        dw = lax.dynamic_update_slice_in_dim(dw, cur + dwj, start, axis=1)
        return (dx, dw), None

    dx0 = jnp.zeros(x.shape, jnp.float32)
    dw0 = jnp.zeros(w.shape, jnp.float32)
    (dx, dw), _ = lax.scan(body, (dx0, dw0), jnp.arange(nb))
    return dx.astype(x.dtype), dw.astype(w.dtype), None


blockwise_cross_entropy.defvjp(_ce_vjp_fwd, _ce_vjp_bwd)


def dense_cross_entropy(x, w, targets):
    """Reference path: materialize logits, log_softmax, gather."""
    logits = jnp.dot(x, w, preferred_element_type=jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, targets[:, None], axis=1)[:, 0]


__all__ = ["blockwise_cross_entropy", "dense_cross_entropy"]
