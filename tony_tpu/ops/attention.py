"""Fused flash attention (forward + backward) as Pallas TPU kernels.

The hot op of the flagship model, tiered by sequence length:

- **VMEM-resident** (L <= 2048): one program per (batch, head), whole
  q/k/v/o in VMEM, fully static tile loops, fused dQ/dK/dV backward.
- **Fused streaming** (L <= 8192): K/V blocks stream HBM -> VMEM with
  double-buffered async DMA and online softmax; the backward is ONE
  kv-block sweep computing dK/dV and accumulating dQ in an [L, D] f32
  VMEM block revisited across the grid — scores/exp recomputed once per
  tile.
- **Split streaming** (beyond): the same forward, with the classic
  two-kernel backward (dQ sweeps KV blocks, dK/dV sweep Q blocks from the
  diagonal down) whose memory stays O(block) — sequence length is bounded
  by HBM, not the 16MB VMEM, which is what makes long-context training
  viable (XLA autodiff of naive attention materializes L x L residuals:
  34GB at L=32k). This tier defaults to a 1024-row q block (measured -14%
  fwd+bwd at 16k vs the 512 the shorter tiers use). Raising the fused
  tier to 16k compiles (8MB dq accumulator) but measured no faster than
  split with the retuned blocks, and 32k blows VMEM — so the boundary
  stays at 8192.

For training, pair long L with `remat_policy="attn"` (models/transformer):
the flash custom_vjp names its (out, lse) residuals so remat saves them
and the backward never re-runs the forward kernel — +7.5%/+14%/+17% step
throughput at L=8k/16k/32k, neutral at 2k.

Forward saves only O and the per-row logsumexp (standard flash
recomputation). Causal masking prunes the KV sweep to lower-triangular
blocks, skipping both the compute AND the DMA of masked blocks (~half the
FLOPs and bytes).

Layout is [B, H, L, D], length tiled to MXU-friendly blocks, scores in f32.
On non-TPU backends the same kernels run in interpreter mode (tests).

No reference counterpart: TonY has no compute layer at all (SURVEY.md §2.3);
this is the TPU-native capability layer of the rebuild.
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.ring_attention import reference_attention

NEG_INF = -1e30
# block sizes from fwd+bwd sweeps on v5e (B=4 H=8 L=2048 D=128, chained
# dependent iterations): 512/512 beats 256/512 by ~8% total and 128/256 by
# ~20%; VMEM stays far under budget (k+v double buffers ~0.5MB at 512x128)
BLOCK_Q = 512
BLOCK_K = 512


def _causal_mask(qi, bq, j, bk, window=None):
    rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
    cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = rows >= cols
    if window is not None:
        # sliding window: each row attends to its last `window` positions
        # (inclusive of itself)
        mask &= cols > rows - window
    return mask


def _attn_mask(qi, bq, j, bk, causal, kv_len, window=None):
    """Combined causal/sliding-window + ragged-KV mask for one [bq, bk]
    score tile, or None when every position is valid (the even, non-causal
    fast path)."""
    mask = _causal_mask(qi, bq, j, bk, window) if causal else None
    if kv_len is not None:
        cols = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        valid = cols < kv_len
        mask = valid if mask is None else (mask & valid)
    return mask


def _n_full_blocks(qi, bq, block_k, hi, causal, kv_len, window):
    """First kv-block index that needs masking, for q-block qi: blocks in
    [lo, n_full) are fully visible and run a mask-free loop body; blocks in
    [n_full, hi) run the masked body. Masked tiles cost ~2x an unmasked
    tile in VPU passes (iota, compare, where) and most causal tiles are
    fully below the diagonal, so the static split wins back real kernel
    time (a runtime cond can't: Mosaic predicates both paths).

    Returns None when the split doesn't apply (sliding window — the band
    has partial tiles on BOTH edges, handled by the single masked loop)."""
    if window is not None:
        return None
    n_full = hi
    if causal:
        # tile j fully visible iff min_row >= max_col:
        # qi*bq >= (j+1)*block_k - 1
        n_full = jnp.minimum(n_full, (qi * bq + 1) // block_k)
    if kv_len is not None:
        n_full = jnp.minimum(n_full, kv_len // block_k)
    return n_full


def _window_lo(qi, bq, block_k, window):
    """First KV block intersecting q-block qi's window band (traced)."""
    if window is None:
        return 0
    return jnp.maximum(0, (qi * bq - window + 1) // block_k)


def _validate_window(causal, window):
    """The band pruning (_window_lo) only matches the mask when causal —
    a non-causal windowed call would skip blocks WITHOUT masking the rest,
    silently corrupting the softmax. Validate at every public entry."""
    if window is not None:
        if not causal:
            raise ValueError("window requires causal=True")
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")


class _Streamer:
    """Double-buffered HBM->VMEM block pipeline over one or more arrays
    (the guide's double-buffering pattern, generalized to N streams that
    advance in lockstep)."""

    def __init__(self, hbm_refs, bufs, sems, batch, block, lo, hi):
        self._hbm = hbm_refs      # list of HBM refs [BH, L, d_i]
        self._bufs = bufs         # list of VMEM scratch [2, block, d_i]
        self._sems = sems         # DMA sems [n_streams, 2]
        self._batch = batch
        self._block = block
        self._lo = lo
        self._hi = hi

    def _dma(self, stream, slot, j):
        return pltpu.make_async_copy(
            self._hbm[stream].at[self._batch, pl.ds(j * self._block, self._block), :],
            self._bufs[stream].at[slot],
            self._sems.at[stream, slot],
        )

    def start(self):
        @pl.when(self._lo < self._hi)
        def _():
            for s in range(len(self._hbm)):
                self._dma(s, 0, self._lo).start()

    def step(self, j):
        """Prefetch j+1, wait for j, return the j blocks (VMEM views)."""
        rel = j - self._lo
        slot = jax.lax.rem(rel, 2)
        nxt = jax.lax.rem(rel + 1, 2)

        @pl.when(j + 1 < self._hi)
        def _():
            for s in range(len(self._hbm)):
                self._dma(s, nxt, j + 1).start()

        for s in range(len(self._hbm)):
            self._dma(s, slot, j).wait()
        return [buf[slot] for buf in self._bufs]


# ------------------------------------------------------------ shared tiles
# The numerically delicate per-tile math lives ONCE here and serves both
# kernel families (streaming and VMEM-resident): a fix in the rescale or
# masking logic cannot diverge between paths.

def _fwd_tile_update(q, k_blk, v_blk, carry, scale, mask, remask):
    """One online-softmax tile: carry = (m, l, acc) f32 running state.
    Operands stay in storage dtype (bf16) into the MXU with f32
    accumulation — upcasting first costs ~4x in matmul passes."""
    m, l, acc = carry
    s = jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                          # [BQ, BK] f32
    if mask is not None:
        s = jnp.where(mask, s, NEG_INF)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    # rows with no valid column in sight (ragged tails; rows whose window
    # band starts past the first swept block) must produce p == 0, which
    # exp(s - m_new) alone can't when m_new is itself NEG_INF — re-mask p.
    # Plain causal never has such rows (kv block 0 is fully valid for every
    # row), so its callers pass remask=False and skip the pass.
    if mask is not None and remask:
        p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    acc_new = acc * corr + jax.lax.dot_general(
        p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return m_new, l_new, acc_new


def _bwd_tile(q_j, do_j, k_blk, v_blk, lse_j, delta_j, scale, mask,
              want_dq=True, want_dkv=True):
    """One backward tile: recompute p = exp(s - lse), ds = p*(dO V^T - delta),
    emitting only the requested gradient pieces so each kernel pays exactly
    its own matmuls. Returns (dq_inc, dk_inc, dv_inc), None where unwanted."""
    s = scale * jax.lax.dot_general(
        q_j, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    p = jnp.exp(s - lse_j)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    dv_inc = None
    if want_dkv:
        dv_inc = jax.lax.dot_general(
            p.astype(do_j.dtype), do_j, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    dp = jax.lax.dot_general(
        do_j, v_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    ds = (p * (dp - delta_j)).astype(q_j.dtype)
    dk_inc = None
    if want_dkv:
        dk_inc = scale * jax.lax.dot_general(
            ds, q_j, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    dq_inc = None
    if want_dq:
        dq_inc = scale * jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    return dq_inc, dk_inc, dv_inc


# ------------------------------------------------------------------ forward

def _fwd_kernel(q_ref, k_hbm, v_hbm, o_ref, lse_ref, k_buf, v_buf, sems,
                *, scale, causal, block_k, kv_len=None, window=None):
    """One (batch*head, q-block) program: stream KV blocks, online softmax.
    Also writes the per-row logsumexp residual for the backward. A sliding
    window additionally prunes blocks BELOW the band — DMA and compute both
    skip everything outside [row-window, row], so cost is O(L*window)."""
    b_ = pl.program_id(0)
    qi = pl.program_id(1)
    # inputs stay in their storage dtype (bf16): the MXU's native mode is
    # low-precision multiply with f32 accumulation (preferred_element_type);
    # upcasting before the dot would force ~4x-slower f32 matmul passes
    q = q_ref[0]                                      # [BQ, D]
    bq, d = q.shape
    nk = k_hbm.shape[1] // block_k
    hi = (
        jnp.minimum(((qi + 1) * bq + block_k - 1) // block_k, nk)
        if causal else nk
    )
    lo = _window_lo(qi, bq, block_k, window)
    stream = _Streamer([k_hbm, v_hbm], [k_buf, v_buf], sems, b_, block_k, lo, hi)
    stream.start()

    remask = window is not None or kv_len is not None

    def make_body(masked):
        def body(j, carry):
            k_blk, v_blk = stream.step(j)
            mask = (
                _attn_mask(qi, bq, j, block_k, causal, kv_len, window)
                if masked else None
            )
            return _fwd_tile_update(q, k_blk, v_blk, carry, scale, mask, remask)
        return body

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    carry = (m0, l0, acc0)
    n_full = _n_full_blocks(qi, bq, block_k, hi, causal, kv_len, window)
    if n_full is None:
        carry = jax.lax.fori_loop(lo, hi, make_body(True), carry)
    else:
        # mask-free sweep over fully-visible tiles, masked sweep for the rest
        n_full = jnp.maximum(n_full, lo)
        carry = jax.lax.fori_loop(lo, n_full, make_body(False), carry)
        carry = jax.lax.fori_loop(n_full, hi, make_body(True), carry)
    m, l, acc = carry
    l_safe = jnp.where(l > 0, l, 1.0)
    o_ref[0] = (acc / l_safe).astype(o_ref.dtype)
    # lse stored lane-major [1, bq]: a [L, 1] layout pads every row to 128
    # lanes in VMEM (16MB at L=32k); [1, L] costs sublane padding only (1MB)
    lse_ref[0, 0] = jnp.where(l[:, 0] > 0, m[:, 0] + jnp.log(l_safe[:, 0]), NEG_INF)


# ------------------------------------------------------------------ backward

def _dq_kernel(q_ref, k_hbm, v_hbm, do_ref, lse_ref, delta_ref, dq_ref,
               k_buf, v_buf, sems, *, scale, causal, block_k, kv_len=None,
               window=None):
    """dQ for one q block: sweep KV blocks.
    ds = p * (dO@V^T - delta); dQ = scale * ds @ K."""
    b_ = pl.program_id(0)
    qi = pl.program_id(1)
    q = q_ref[0]                                       # [BQ, D] storage dtype
    do = do_ref[0]
    lse = lse_ref[0, 0][:, None]                       # [BQ, 1]
    delta = delta_ref[0, 0][:, None]
    bq, d = q.shape
    nk = k_hbm.shape[1] // block_k
    hi = (
        jnp.minimum(((qi + 1) * bq + block_k - 1) // block_k, nk)
        if causal else nk
    )
    lo = _window_lo(qi, bq, block_k, window)
    stream = _Streamer([k_hbm, v_hbm], [k_buf, v_buf], sems, b_, block_k, lo, hi)
    stream.start()

    def make_body(masked):
        def body(j, dq):
            k_blk, v_blk = stream.step(j)
            mask = (
                _attn_mask(qi, bq, j, block_k, causal, kv_len, window)
                if masked else None
            )
            dq_inc, _, _ = _bwd_tile(
                q, do, k_blk, v_blk, lse, delta, scale, mask, want_dkv=False
            )
            return dq + dq_inc
        return body

    dq = jnp.zeros((bq, d), jnp.float32)
    n_full = _n_full_blocks(qi, bq, block_k, hi, causal, kv_len, window)
    if n_full is None:
        dq = jax.lax.fori_loop(lo, hi, make_body(True), dq)
    else:
        n_full = jnp.maximum(n_full, lo)
        dq = jax.lax.fori_loop(lo, n_full, make_body(False), dq)
        dq = jax.lax.fori_loop(n_full, hi, make_body(True), dq)
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _kv_sweep_kernel(q_hbm, k_ref, v_ref, do_hbm, lse_ref, delta_ref, *refs,
                     scale, causal, block_q, kv_len=None, window=None,
                     fused=False):
    """One (batch*head, kv-block) program sweeping Q blocks — BOTH streaming
    backward tiers share this body:

    - split (fused=False): emits dK/dV only (refs = dk, dv, scratch). The
      companion _dq_kernel recomputes scores for dQ; memory stays O(block).
    - fused (fused=True): refs also lead with a dq accumulator whose block
      index map is constant along the kv grid dim, so Pallas keeps it
      VMEM-resident across the sequential revisits (race-free: TPU grid
      iterations execute in order on the core). Each tile's scores/exp are
      recomputed ONCE instead of once per split kernel, at the price of an
      [L, D] f32 dq block (FUSED_STREAM_MAX_L bounds it).

    Sweep bounds: from the diagonal down when causal; a sliding window also
    bounds the sweep from ABOVE — rows past col+window can't see this
    block. dV = p^T @ dO; dK = scale * ds^T @ Q; dQ += scale * ds @ K.
    Q/dO stream from HBM; lse/delta are 4B/row and ride in VMEM whole."""
    if fused:
        dq_ref, dk_ref, dv_ref, q_buf, do_buf, sems = refs
    else:
        dq_ref = None
        dk_ref, dv_ref, q_buf, do_buf, sems = refs
    b_ = pl.program_id(0)
    ki = pl.program_id(1)
    k_blk = k_ref[0]                                   # [BK, D] storage dtype
    v_blk = v_ref[0]
    bk, d = k_blk.shape
    nq = q_hbm.shape[1] // block_q

    if fused:
        @pl.when(ki == 0)
        def _init_dq():
            dq_ref[0] = jnp.zeros(dq_ref.shape[1:], dq_ref.dtype)

    lo = (ki * bk) // block_q if causal else 0
    hi = nq
    if window is not None:
        # rows seeing col c satisfy row < c + window; last col of this
        # block is ki*bk + bk - 1
        hi = jnp.minimum(nq, (ki * bk + bk - 1 + window + block_q - 1) // block_q)
    stream = _Streamer(
        [q_hbm, do_hbm], [q_buf, do_buf], sems, b_, block_q, lo, hi,
    )
    stream.start()

    # the split tier never masks padded KV columns here (its dk/dv rows for
    # padded positions are sliced away by the caller) — but the fused tier's
    # dQ really consumes them, so it must
    kv_len_eff = kv_len if fused else None

    def make_body(masked):
        def body(j, carry):
            dk, dv = carry
            q_j, do_j = stream.step(j)
            lse_j = lse_ref[0, 0, pl.ds(j * block_q, block_q)][:, None]   # [BQ, 1]
            delta_j = delta_ref[0, 0, pl.ds(j * block_q, block_q)][:, None]
            mask = (
                _attn_mask(j, block_q, ki, bk, causal, kv_len_eff, window)
                if masked else None
            )
            dq_inc, dk_inc, dv_inc = _bwd_tile(
                q_j, do_j, k_blk, v_blk, lse_j, delta_j, scale, mask,
                want_dq=fused,
            )
            if fused:
                cur = dq_ref[0, pl.ds(j * block_q, block_q), :]
                dq_ref[0, pl.ds(j * block_q, block_q), :] = (
                    cur + dq_inc.astype(dq_ref.dtype)
                )
            return dk + dk_inc, dv + dv_inc
        return body

    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    carry = (dk0, dv0)
    always_mask = kv_len_eff is not None
    if not causal:
        dk, dv = jax.lax.fori_loop(lo, hi, make_body(always_mask), carry)
    elif window is not None or always_mask:
        # band-pruned sweep (partial tiles on both edges) or ragged-KV dq
        # masking: single masked loop
        dk, dv = jax.lax.fori_loop(lo, hi, make_body(True), carry)
    else:
        # roles swapped vs the fwd/dq sweeps: rows are q blocks (j), cols
        # this kv block (ki). Masked (diagonal) tiles come FIRST in the
        # sweep; q blocks past the diagonal see the whole kv block.
        m_end = jnp.minimum(
            hi, -(-((ki + 1) * bk - 1) // block_q)  # ceil division
        )
        carry = jax.lax.fori_loop(lo, m_end, make_body(True), carry)
        dk, dv = jax.lax.fori_loop(m_end, hi, make_body(False), carry)
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# ---------------------------------------------------- VMEM-resident kernels
#
# At L <= RESIDENT_MAX_L (and D <= 128) one (batch, head)'s whole q/k/v/o —
# plus the f32 dq accumulator in the backward — fits VMEM, so the kernel
# needs NO per-block DMA choreography at all: grid (B*H,), Pallas pipelines
# whole [L, D] blocks between grid steps, and the tile loops are plain
# Python loops over static slices (every causal/ragged/window decision is
# resolved at trace time — full tiles compile with zero masking code).
# The backward is additionally FUSED: one sweep computes dK, dV and dQ,
# recomputing scores/exp once per tile instead of once in each of the
# dq/dkv kernels. Longer sequences fall back to the streaming kernels
# above, which keep O(block) VMEM.

# 2048: at 4096 the fully-unrolled tile loops blow Mosaic's scoped-VMEM
# stack (~40MB of live temporaries vs the 16MB budget)
RESIDENT_MAX_L = 2048
# mid tier for the backward: one FUSED streaming sweep (dq accumulated in a
# VMEM output block revisited across the kv grid dimension) instead of the
# split dq/dkv kernels — saves one score/exp recompute per tile. The dq
# accumulator is [L, D] f32 per (batch, head): 4MB at L=8192; beyond that
# the split O(block)-memory kernels take over.
FUSED_STREAM_MAX_L = 8192


def _static_tile_kind(qi, bq, j, bk, causal, kv_len, window):
    """Python-level (static) classification of tile (qi, j): 'skip' (fully
    masked — don't emit code), 'full' (no mask), or 'partial'."""
    row_lo, row_hi = qi * bq, (qi + 1) * bq - 1
    col_lo, col_hi = j * bk, (j + 1) * bk - 1
    if causal and col_lo > row_hi:
        return "skip"
    if window is not None and col_hi < row_lo - window + 1:
        return "skip"
    if kv_len is not None and col_lo >= kv_len:
        return "skip"
    full = True
    if causal and col_hi > row_lo:
        full = False
    if window is not None and col_lo < row_hi - window + 1:
        full = False
    if kv_len is not None and col_hi >= kv_len:
        full = False
    return "full" if full else "partial"


def _fwd_kernel_resident(q_ref, k_ref, v_ref, o_ref, lse_ref,
                         *, scale, causal, block_q, block_k,
                         kv_len=None, window=None):
    """One (batch*head) program: everything VMEM-resident, static tile loops."""
    lq, d = q_ref.shape[1], q_ref.shape[2]
    lk = k_ref.shape[1]
    nq, nk = lq // block_q, lk // block_k

    remask = window is not None or kv_len is not None
    for qi in range(nq):
        q = q_ref[0, qi * block_q:(qi + 1) * block_q, :]
        carry = (
            jnp.full((block_q, 1), NEG_INF, jnp.float32),
            jnp.zeros((block_q, 1), jnp.float32),
            jnp.zeros((block_q, d), jnp.float32),
        )
        for j in range(nk):
            kind = _static_tile_kind(
                qi, block_q, j, block_k, causal, kv_len, window
            )
            if kind == "skip":
                continue
            k_blk = k_ref[0, j * block_k:(j + 1) * block_k, :]
            v_blk = v_ref[0, j * block_k:(j + 1) * block_k, :]
            mask = (
                _attn_mask(qi, block_q, j, block_k, causal, kv_len, window)
                if kind == "partial" else None
            )
            carry = _fwd_tile_update(q, k_blk, v_blk, carry, scale, mask,
                                     remask)
        m, l, acc = carry
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0, qi * block_q:(qi + 1) * block_q, :] = (
            (acc / l_safe).astype(o_ref.dtype)
        )
        lse_ref[0, 0, qi * block_q:(qi + 1) * block_q] = jnp.where(
            l[:, 0] > 0, m[:, 0] + jnp.log(l_safe[:, 0]), NEG_INF
        )


def _bwd_kernel_resident(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dk_ref, dv_ref,
                         *, scale, causal, block_q, block_k,
                         kv_len=None, window=None):
    """Fused dQ/dK/dV for one (batch*head): a single sweep recomputes each
    tile's scores/exp ONCE (the split dq/dkv kernels do it twice) and
    accumulates dQ in the f32 VMEM output ref across kv blocks."""
    lq, d = q_ref.shape[1], q_ref.shape[2]
    lk = k_ref.shape[1]
    nq, nk = lq // block_q, lk // block_k

    dq_ref[0] = jnp.zeros((lq, d), dq_ref.dtype)
    for ki in range(nk):
        k_blk = k_ref[0, ki * block_k:(ki + 1) * block_k, :]
        v_blk = v_ref[0, ki * block_k:(ki + 1) * block_k, :]
        dk = jnp.zeros((block_k, d), jnp.float32)
        dv = jnp.zeros((block_k, d), jnp.float32)
        for j in range(nq):
            kind = _static_tile_kind(
                j, block_q, ki, block_k, causal, kv_len, window
            )
            if kind == "skip":
                continue
            sl = slice(j * block_q, (j + 1) * block_q)
            q_j = q_ref[0, sl, :]
            do_j = do_ref[0, sl, :]
            lse_j = lse_ref[0, 0, sl][:, None]
            delta_j = delta_ref[0, 0, sl][:, None]
            mask = (
                _attn_mask(j, block_q, ki, block_k, causal, kv_len, window)
                if kind == "partial" else None
            )
            dq_inc, dk_inc, dv_inc = _bwd_tile(
                q_j, do_j, k_blk, v_blk, lse_j, delta_j, scale, mask
            )
            dk = dk + dk_inc
            dv = dv + dv_inc
            dq_ref[0, sl, :] += dq_inc.astype(dq_ref.dtype)
        dk_ref[0, ki * block_k:(ki + 1) * block_k, :] = dk.astype(dk_ref.dtype)
        dv_ref[0, ki * block_k:(ki + 1) * block_k, :] = dv.astype(dv_ref.dtype)


def _use_resident(lq, lk, d):
    """Whole-sequence VMEM residency budget (see section comment)."""
    return lq <= RESIDENT_MAX_L and lk <= RESIDENT_MAX_L and d <= 128


def _block(block, l):
    """Kernel block size for a length-l axis: the configured block, shrunk for
    short sequences but kept a multiple of 128 — Mosaic requires sliced-ref
    shapes aligned to the (8, 128) tiling (HBM row slices AND the lane-major
    lse/delta lane slices), so arbitrary l (e.g. 300) cannot be a block."""
    return min(block, max(128, -(-l // 128) * 128))


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    rem = size % multiple
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, multiple - rem)
    return jnp.pad(x, pad)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret", "window"),
)
def _flash_fwd(q, k, v, causal, scale, block_q=BLOCK_Q, block_k=BLOCK_K,
               interpret=False, window=None):
    """q,k,v: [B, H, L, D] -> (out [B,H,L,D], lse [B,H,L] f32)."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    scale = (d ** -0.5) if scale is None else scale
    block_q = _block(block_q, lq)
    block_k = _block(block_k, lk)
    qp = _pad_to(q, 2, block_q)
    kp = _pad_to(k, 2, block_k)
    vp = _pad_to(v, 2, block_k)
    # ragged L_k: kernel masks padded KV columns (kv_len is static -> the
    # even case compiles with no mask at all)
    kv_len = lk if kp.shape[2] != lk else None

    bh = b * h
    qf = qp.reshape(bh, qp.shape[2], d)
    kf = kp.reshape(bh, kp.shape[2], d)
    vf = vp.reshape(bh, vp.shape[2], d)
    nq = qf.shape[1] // block_q

    if _use_resident(qf.shape[1], kf.shape[1], d):
        out, lse = pl.pallas_call(
            functools.partial(
                _fwd_kernel_resident, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k, kv_len=kv_len,
                window=window,
            ),
            grid=(bh,),
            in_specs=[
                pl.BlockSpec((1, qf.shape[1], d), lambda b_: (b_, 0, 0)),
                pl.BlockSpec((1, kf.shape[1], d), lambda b_: (b_, 0, 0)),
                pl.BlockSpec((1, kf.shape[1], d), lambda b_: (b_, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, qf.shape[1], d), lambda b_: (b_, 0, 0)),
                pl.BlockSpec((1, 1, qf.shape[1]), lambda b_: (b_, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(qf.shape, q.dtype),
                jax.ShapeDtypeStruct((bh, 1, qf.shape[1]), jnp.float32),
            ],
            interpret=interpret,
        )(qf, kf, vf)
    else:
        out, lse = pl.pallas_call(
            functools.partial(_fwd_kernel, scale=scale, causal=causal,
                              block_k=block_k, kv_len=kv_len, window=window),
            grid=(bh, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b_, i: (b_, i, 0)),
                pl.BlockSpec(memory_space=pl.ANY),   # K stays in HBM, DMA'd
                pl.BlockSpec(memory_space=pl.ANY),   # V stays in HBM, DMA'd
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b_, i: (b_, i, 0)),
                pl.BlockSpec((1, 1, block_q), lambda b_, i: (b_, 0, i)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct(qf.shape, q.dtype),
                jax.ShapeDtypeStruct((bh, 1, qf.shape[1]), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, block_k, d), k.dtype),
                pltpu.VMEM((2, block_k, d), v.dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
            interpret=interpret,
        )(qf, kf, vf)
    out = out.reshape(b, h, qf.shape[1], d)[:, :, :lq, :]
    lse = lse.reshape(b, h, qf.shape[1])[:, :, :lq]
    return out, lse


@functools.partial(
    jax.jit,
    static_argnames=("causal", "scale", "block_q", "block_k", "interpret", "window"),
)
def _flash_bwd(q, k, v, o, lse, g, causal, scale,
               block_q=BLOCK_Q, block_k=BLOCK_K, interpret=False, g_lse=None,
               window=None):
    b, h, lq, d = q.shape
    lk = k.shape[2]
    scale = (d ** -0.5) if scale is None else scale
    if (block_q, block_k) == (BLOCK_Q, BLOCK_K) and lq > FUSED_STREAM_MAX_L:
        # long-context split tier: doubling the q block amortizes per-tile
        # overhead over more rows — measured fwd+bwd 27.3 -> 23.4 ms/iter
        # (-14%) at L=16384 and -5% at L=32768 on v5e (1024x512; both-1024
        # and k-1024 measured no better, and bigger blocks blow VMEM)
        block_q = 1024
    block_q = _block(block_q, lq)
    block_k = _block(block_k, lk)

    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)  # [B,H,L]
    if g_lse is not None:
        # cotangent on the lse output: d lse_i/d s_ij = p_ij, so the extra
        # ds term is g_lse_i * p_ij — absorbed as delta' = delta - g_lse in
        # ds = p * (dp - delta'). dV is untouched (no lse dependence).
        delta = delta - g_lse.astype(jnp.float32)

    qp, gp = _pad_to(q, 2, block_q), _pad_to(g, 2, block_q)
    kp, vp = _pad_to(k, 2, block_k), _pad_to(v, 2, block_k)
    kv_len = lk if kp.shape[2] != lk else None
    # padded q rows: lse=+big -> p = exp(s - lse) = 0; delta=0
    # (NEG_INF here would make p = exp(s + 1e30) = inf -> NaN dK/dV)
    lsep = _pad_to(lse, 2, block_q)
    deltap = _pad_to(delta, 2, block_q)
    if lsep.shape[2] != lse.shape[2]:
        pad_rows = lsep.shape[2] - lse.shape[2]
        lsep = lsep.at[:, :, -pad_rows:].set(-NEG_INF)
    # lane-major layout (see _fwd_kernel note)

    bh = b * h
    lqp, lkp = qp.shape[2], kp.shape[2]
    qf = qp.reshape(bh, lqp, d)
    kf = kp.reshape(bh, lkp, d)
    vf = vp.reshape(bh, lkp, d)
    gf = gp.reshape(bh, lqp, d)
    lsef = lsep.reshape(bh, 1, lqp)
    deltaf = deltap.reshape(bh, 1, lqp)

    nq = lqp // block_q
    nk = lkp // block_k

    if _use_resident(lqp, lkp, d):
        # fused resident backward: dq accumulates in f32 (the in-ref
        # accumulation across kv blocks must not round in bf16)
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_kernel_resident, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k, kv_len=kv_len,
                window=window,
            ),
            grid=(bh,),
            in_specs=[
                pl.BlockSpec((1, lqp, d), lambda b_: (b_, 0, 0)),
                pl.BlockSpec((1, lkp, d), lambda b_: (b_, 0, 0)),
                pl.BlockSpec((1, lkp, d), lambda b_: (b_, 0, 0)),
                pl.BlockSpec((1, lqp, d), lambda b_: (b_, 0, 0)),
                pl.BlockSpec((1, 1, lqp), lambda b_: (b_, 0, 0)),
                pl.BlockSpec((1, 1, lqp), lambda b_: (b_, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, lqp, d), lambda b_: (b_, 0, 0)),
                pl.BlockSpec((1, lkp, d), lambda b_: (b_, 0, 0)),
                pl.BlockSpec((1, lkp, d), lambda b_: (b_, 0, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, lqp, d), jnp.float32),
                jax.ShapeDtypeStruct(kf.shape, k.dtype),
                jax.ShapeDtypeStruct(vf.shape, v.dtype),
            ],
            interpret=interpret,
        )(qf, kf, vf, gf, lsef, deltaf)
        dq = dq.astype(q.dtype)
        dq = dq.reshape(b, h, lqp, d)[:, :, :lq, :]
        dk = dk.reshape(b, h, lkp, d)[:, :, :lk, :]
        dv = dv.reshape(b, h, lkp, d)[:, :, :lk, :]
        return dq, dk, dv

    if lqp <= FUSED_STREAM_MAX_L and lkp <= FUSED_STREAM_MAX_L and d <= 128:
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _kv_sweep_kernel, scale=scale, causal=causal,
                block_q=block_q, kv_len=kv_len, window=window, fused=True,
            ),
            grid=(bh, nk),
            in_specs=[
                pl.BlockSpec(memory_space=pl.ANY),   # Q in HBM, streamed
                pl.BlockSpec((1, block_k, d), lambda b_, i: (b_, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b_, i: (b_, i, 0)),
                pl.BlockSpec(memory_space=pl.ANY),   # dO in HBM, streamed
                pl.BlockSpec((1, 1, lqp), lambda b_, i: (b_, 0, 0)),
                pl.BlockSpec((1, 1, lqp), lambda b_, i: (b_, 0, 0)),
            ],
            out_specs=[
                # constant index along the kv dim: VMEM-resident across the
                # revisits, flushed when b_ advances — the dq accumulator
                pl.BlockSpec((1, lqp, d), lambda b_, i: (b_, 0, 0)),
                pl.BlockSpec((1, block_k, d), lambda b_, i: (b_, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b_, i: (b_, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, lqp, d), jnp.float32),
                jax.ShapeDtypeStruct(kf.shape, k.dtype),
                jax.ShapeDtypeStruct(vf.shape, v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((2, block_q, d), q.dtype),
                pltpu.VMEM((2, block_q, d), g.dtype),
                pltpu.SemaphoreType.DMA((2, 2)),
            ],
            interpret=interpret,
        )(qf, kf, vf, gf, lsef, deltaf)
        dq = dq.astype(q.dtype).reshape(b, h, lqp, d)[:, :, :lq, :]
        dk = dk.reshape(b, h, lkp, d)[:, :, :lk, :]
        dv = dv.reshape(b, h, lkp, d)[:, :, :lk, :]
        return dq, dk, dv

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_k=block_k, kv_len=kv_len, window=window),
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, i: (b_, i, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # K in HBM
            pl.BlockSpec(memory_space=pl.ANY),   # V in HBM
            pl.BlockSpec((1, block_q, d), lambda b_, i: (b_, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda b_, i: (b_, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda b_, i: (b_, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b_, i: (b_, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        scratch_shapes=[
            pltpu.VMEM((2, block_k, d), k.dtype),
            pltpu.VMEM((2, block_k, d), v.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, lsef, deltaf)

    dk, dv = pl.pallas_call(
        functools.partial(_kv_sweep_kernel, scale=scale, causal=causal,
                          block_q=block_q, window=window),
        grid=(bh, nk),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),   # Q in HBM
            pl.BlockSpec((1, block_k, d), lambda b_, i: (b_, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, i: (b_, i, 0)),
            pl.BlockSpec(memory_space=pl.ANY),   # dO in HBM
            pl.BlockSpec((1, 1, lqp), lambda b_, i: (b_, 0, 0)),  # lse (tiny)
            pl.BlockSpec((1, 1, lqp), lambda b_, i: (b_, 0, 0)),  # delta (tiny)
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b_, i: (b_, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b_, i: (b_, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(kf.shape, k.dtype),
            jax.ShapeDtypeStruct(vf.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((2, block_q, d), q.dtype),
            pltpu.VMEM((2, block_q, d), g.dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
        ],
        interpret=interpret,
    )(qf, kf, vf, gf, lsef, deltaf)

    dq = dq.reshape(b, h, lqp, d)[:, :, :lq, :]
    dk = dk.reshape(b, h, lkp, d)[:, :, :lk, :]
    dv = dv.reshape(b, h, lkp, d)[:, :, :lk, :]
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention_with_lse(q, k, v, causal=True, scale=None, window=None):
    """Flash attention that also returns the per-row logsumexp, [B, H, L, D]
    layout -> (out [B,H,L,D], lse [B,H,L] f32).

    The lse output is differentiable (the backward folds its cotangent into
    the delta residual), which is what makes flash blocks composable: a
    caller can merge partial results from disjoint KV shards as
    ``logaddexp``-weighted sums — ring attention does exactly that — and
    autodiff still produces exact gradients. No fallback: callers must check
    ``flash_supported`` (ring attention does)."""
    _validate_window(causal, window)
    return _flash_fwd(q, k, v, causal, scale, interpret=not _on_tpu(),
                      window=window)


def _lse_vjp_fwd(q, k, v, causal, scale, window):
    out, lse = _flash_fwd(q, k, v, causal, scale, interpret=not _on_tpu(),
                          window=window)
    # name the residuals the backward actually consumes so a remat policy
    # (models.transformer remat_policy="attn") can pin them: with out+lse
    # saved, the rematerialized backward's recompute of this forward is
    # dead code (all its outputs are known) and the flash kernel runs once
    # per step instead of twice
    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return (out, lse), (q, k, v, out, lse)


def _lse_vjp_bwd(causal, scale, window, res, g):
    q, k, v, o, lse = res
    g_out, g_lse = g
    return _flash_bwd(
        q, k, v, o, lse, g_out, causal, scale,
        interpret=not _on_tpu(), g_lse=g_lse, window=window,
    )


flash_attention_with_lse.defvjp(_lse_vjp_fwd, _lse_vjp_bwd)


def flash_supported(q: jax.Array) -> bool:
    """Support envelope of the Pallas kernels, [B, H, L, D] layout: the
    streamer DMAs [block, D] slices and Mosaic requires the lane (last)
    dimension of a sliced ref to be a multiple of the 128-wide tiling.
    Ragged lengths are handled in-kernel (padded KV columns masked, padded
    Q rows zeroed via the lse residual)."""
    return q.shape[-1] % 128 == 0


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: float | None = None,
    window: int | None = None,
) -> jax.Array:
    """Fused attention, [B, H, L, D] layout. Pallas-compiled on TPU,
    interpreted elsewhere; flash backward (O(block) memory both ways).

    ``window`` enables sliding-window (local) attention: each position
    attends to its last `window` positions inclusive; block pruning skips
    the DMA and compute of everything outside the band, so cost becomes
    O(L * window) instead of O(L^2). Requires causal=True.

    Shapes outside the kernel envelope (see flash_supported) fall back to
    naive XLA attention — full L x L scores, O(L^2) memory — with a one-time
    warning, since at long context that is a real memory cliff."""
    _validate_window(causal, window)
    tiling_ok = not _on_tpu() or flash_supported(q)  # interpret: no tiling
    if not tiling_ok:
        warnings.warn(
            f"flash_attention: shape q={q.shape} causal={causal} is outside "
            "the Pallas kernel envelope (head_dim % 128); falling back to "
            "naive XLA attention with full L x L scores — expect O(L^2) "
            "memory",
            stacklevel=2,
        )
        out = reference_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=causal, scale=scale,
            window=window,
        )
        return out.transpose(0, 2, 1, 3)
    # single custom_vjp path; the unused lse cotangent arrives as zeros and
    # costs one elementwise subtract in the backward
    return flash_attention_with_lse(q, k, v, causal, scale, window)[0]


def attention_blhd(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = True, scale: float | None = None,
    window: int | None = None,
) -> jax.Array:
    """Convenience wrapper for the [B, L, H, D] model layout."""
    out = flash_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal=causal, scale=scale, window=window,
    )
    return out.transpose(0, 2, 1, 3)


def chunked_reference_attention(q, k, v, causal=True, q_block: int = 512):
    """The strongest long-context attention plain XLA can offer without a
    fused kernel: queries processed in blocks (lax.map) with jax.checkpoint
    on the per-block body, so neither forward nor backward materializes the
    [L, L] score matrix — only per-block [B, H, bq, L] scores, recomputed
    in the backward. The materializing `reference_attention` is
    uncompilable at L=16k on a 16GB chip (its L x L f32 residuals exceed
    HBM); this is the honest XLA baseline the flash kernel is benchmarked
    against there (bench_transformer.py), and a usable fallback for
    platforms without Pallas. q/k/v: [B, H, L, D]."""
    b, h, L, d = q.shape
    nb = L // q_block
    if nb * q_block != L:
        raise ValueError(f"L={L} not divisible by q_block={q_block}")
    scale = d ** -0.5

    @jax.checkpoint
    def block(qb, offset):
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", qb, k, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            qpos = offset + jnp.arange(L // nb)
            mask = jnp.arange(L)[None, :] <= qpos[:, None]
            s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    qb = q.reshape(b, h, nb, q_block, d).transpose(2, 0, 1, 3, 4)
    offs = jnp.arange(nb) * q_block
    out = jax.lax.map(lambda args: block(*args), (qb, offs))
    return out.transpose(1, 2, 0, 3, 4).reshape(b, h, L, d)


__all__ = [
    "flash_attention", "flash_attention_with_lse", "flash_supported",
    "attention_blhd", "reference_attention", "chunked_reference_attention",
]
