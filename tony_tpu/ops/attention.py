"""Fused flash attention as a Pallas TPU kernel.

The hot op of the flagship model. Streams K/V blocks through VMEM with online
softmax so the L x L score matrix never hits HBM; causal masking prunes the
KV loop to the lower-triangular blocks, so the kernel does ~half the FLOPs of
dense attention. Layout is [B, H, L, D] with the length dim tiled to MXU
-friendly 128 blocks and scores accumulated in f32 (bf16 inputs stay bf16 on
the matmul operands — MXU native).

On non-TPU backends the same kernel runs in interpreter mode (tests), and the
backward pass recomputes attention under jax.grad of the reference
implementation (memory-lean: no L x L residuals saved).

No reference counterpart: TonY has no compute layer at all (SURVEY.md §2.3);
this is the TPU-native capability layer of the rebuild.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.ring_attention import reference_attention

NEG_INF = -1e30


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_k):
    """One (batch*head, q-block) program: stream KV blocks, online softmax."""
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # [BQ, D]
    bq, d = q.shape
    lk = k_ref.shape[1]
    nk = lk // block_k

    if causal:
        # only KV blocks that intersect the lower triangle of this q block
        hi = jnp.minimum(((qi + 1) * bq + block_k - 1) // block_k, nk)
    else:
        hi = nk

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                              # [BQ, BK]
        if causal:
            rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 0)
            cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (bq, block_k), 1)
            s = jnp.where(rows >= cols, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * corr + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((bq, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq, 1), jnp.float32)
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m, l, acc = jax.lax.fori_loop(0, hi, body, (m0, l0, acc0))
    l = jnp.where(l > 0, l, 1.0)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _pad_to(x, axis, multiple):
    size = x.shape[axis]
    rem = size % multiple
    if rem == 0:
        return x, size
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, multiple - rem)
    return jnp.pad(x, pad), size


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_k", "interpret")
)
def _flash_fwd(q, k, v, causal, scale, block_q, block_k, interpret):
    """q,k,v: [B, H, L, D] -> [B, H, L, D]."""
    b, h, lq, d = q.shape
    lk = k.shape[2]
    scale = (d ** -0.5) if scale is None else scale

    block_q = min(block_q, max(8, lq))
    block_k = min(block_k, max(8, lk))
    q, lq0 = _pad_to(q, 2, block_q)
    k, _ = _pad_to(k, 2, block_k)
    v, _ = _pad_to(v, 2, block_k)
    # padded KV positions must not attend: handled by causal mask when causal
    # (padded q rows are dropped), but for non-causal we mask via key padding
    if not causal and k.shape[2] != lk:
        raise NotImplementedError("non-causal flash requires L_k % block_k == 0")

    bh = b * h
    qf = q.reshape(bh, q.shape[2], d)
    kf = k.reshape(bh, k.shape[2], d)
    vf = v.reshape(bh, v.shape[2], d)
    nq = qf.shape[1] // block_q

    out = pl.pallas_call(
        functools.partial(
            _flash_kernel, scale=scale, causal=causal, block_k=block_k
        ),
        grid=(bh, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b_, i: (b_, i, 0)),
            pl.BlockSpec((1, kf.shape[1], d), lambda b_, i: (b_, 0, 0)),
            pl.BlockSpec((1, vf.shape[1], d), lambda b_, i: (b_, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b_, i: (b_, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, q.shape[2], d)[:, :, :lq0, :]


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_attention(q, k, v, causal, scale):
    # block sizes from a sweep on v5e: bq=256/bk=512 runs ~1.75x faster than
    # 128/128 and ~2.7x faster than XLA's fused attention at L=2048, D=128
    return _flash_fwd(
        q, k, v, causal, scale, block_q=256, block_k=512,
        interpret=not _on_tpu(),
    )


def _fwd(q, k, v, causal, scale):
    return _flash_attention(q, k, v, causal, scale), (q, k, v)


def _bwd(causal, scale, res, g):
    # recompute-based backward: O(L/B-block) extra memory vs saving P; the
    # L x L matrix exists only inside XLA's fused gradient of the reference
    q, k, v = res

    def ref(q, k, v):
        # reference_attention expects [B, L, H, D]
        o = reference_attention(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), causal=causal, scale=scale,
        )
        return o.transpose(0, 2, 1, 3)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


_flash_attention.defvjp(_fwd, _bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = True,
    scale: float | None = None,
) -> jax.Array:
    """Fused attention, [B, H, L, D] layout. Pallas-compiled on TPU,
    interpreted elsewhere; differentiable via recompute backward."""
    return _flash_attention(q, k, v, causal, scale)


def attention_blhd(
    q: jax.Array, k: jax.Array, v: jax.Array,
    causal: bool = True, scale: float | None = None,
) -> jax.Array:
    """Convenience wrapper for the [B, L, H, D] model layout."""
    out = flash_attention(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), causal=causal, scale=scale,
    )
    return out.transpose(0, 2, 1, 3)
