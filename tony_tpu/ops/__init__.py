"""TPU kernels and fused ops (Pallas where it pays, XLA fusion elsewhere)."""

from .attention import attention_blhd, flash_attention

__all__ = ["flash_attention", "attention_blhd"]
