"""TPU kernels and fused ops (Pallas where it pays, XLA fusion elsewhere)."""

from .attention import attention_blhd, flash_attention, flash_attention_with_lse
from .cross_entropy import blockwise_cross_entropy, dense_cross_entropy

__all__ = [
    "flash_attention", "flash_attention_with_lse", "attention_blhd",
    "blockwise_cross_entropy", "dense_cross_entropy",
]
