"""Flash-decode: split-KV cached attention for single-token decode steps.

The XLA einsum formulation of decode attention (generate._cached_attention)
measures ~4.3x its HBM bound at 16k context on v5e — the [kvH, M, D]
cache read does not stream well through the einsum+mask+softmax graph.
This kernel is the decode-side counterpart of the training flash kernel
(ops/attention.py): grid over (batch, kv head, KV blocks), each program
streams one [block_k, D] cache block through the online-softmax update
with f32 running (m, l, acc) state in VMEM scratch, writing the
normalized output on the last block. Pallas's grid pipeline overlaps the
HBM block fetches with compute — the kernel's cost is the cache bytes.

GQA folds the q heads to [kvH, rep, D]; each program's matmuls are
[rep, D] x [D, block_k] — skinny on the MXU, but decode attention is
bandwidth-bound, so the streamed cache bytes are the cost that matters.

int8 caches stream as int8 (HALF the bytes — the entire point of the
quantized cache) and dequantize per block in VMEM: K's per-position
scales fold into the score columns AFTER the matmul, V's scales
pre-multiply the (tiny) probability row — the same scale-folding
discipline as the XLA path, so no dequantized copy of the cache ever
exists anywhere.

The current token's K/V must already be written to the cache (the
write-then-attend order generate uses); masking is by absolute position:
key_pos <= q_pos = length, with the optional sliding-window band.

No reference counterpart: TonY has no compute layer (SURVEY.md §2.3).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_kernel(len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref, o_ref,
                   m_ref, l_ref, acc_ref, *, scale, block_k, n_blocks,
                   window):
    """One (b, kv-head, KV-block) grid step of the online softmax. The
    grid's last dimension iterates sequentially, so the f32 (m, l, acc)
    scratch carries across a head's blocks; init at block 0, normalize
    and emit at the last block."""
    j = pl.program_id(2)
    length = len_ref[0]

    @pl.when(j == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0, 0]                                   # [rep, D]
    d = q.shape[-1]
    k_blk = k_ref[...].reshape(block_k, d)
    s = jax.lax.dot_general(
        q, k_blk.astype(q.dtype), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale                                          # [rep, block_k] f32
    if ks_ref is not None:
        s = s * ks_ref[...].reshape(1, block_k).astype(jnp.float32)
    key_pos = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, s.shape, dimension=1)
    mask = key_pos <= length
    if window:
        mask &= key_pos > length - window
    s = jnp.where(mask, s, NEG_INF)

    m_prev, l_prev = m_ref[...], l_ref[...]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    # blocks fully past the valid range (or before the window band) have
    # no valid column: exp(NEG_INF - NEG_INF) = 1 must be re-masked to 0
    p = jnp.where(mask, p, 0.0)
    corr = jnp.exp(m_prev - m_new)
    # the softmax denominator sums the RAW probabilities; V's dequant
    # scale applies only to the value accumulation below
    l_ref[...] = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
    m_ref[...] = m_new
    # the tail block's out-of-bounds lanes hold unspecified values; p is 0
    # there but 0 * NaN = NaN, so the V operand (and its scale) must be
    # zeroed at masked columns before the accumulation. The [block_k, 1]
    # mask is built with its own iota — Mosaic cannot transpose an i1
    # vector ("insertion of minor dim" is 32-bit-only).
    if vs_ref is not None:
        vs = vs_ref[...].reshape(1, block_k).astype(jnp.float32)
        p = p * jnp.where(mask[:1], vs, 0.0)
    key_col = j * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_k, 1), 0)
    col_valid = key_col <= length
    if window:
        col_valid &= key_col > length - window
    v_blk = v_ref[...].reshape(block_k, d)
    # the PV accumulation keeps p in f32 (v upcast too): casting the
    # probabilities to bf16 here made greedy tokens drift vs the XLA
    # einsum path (f32-accumulated) right where the M>=4096 kernel gate
    # engages. The matmul is cache-bandwidth-bound — the [rep, block_k]
    # prob operand is tiny, so the f32 MXU pass costs nothing measurable.
    v_blk = jnp.where(col_valid, v_blk.astype(jnp.float32), 0.0)
    acc_ref[...] = acc_ref[...] * corr + jax.lax.dot_general(
        p, v_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )

    @pl.when(j == n_blocks - 1)
    def _finish():
        l = l_ref[...]
        l_safe = jnp.where(l > 0, l, 1.0)
        o_ref[0, 0] = (acc_ref[...] / l_safe).astype(o_ref.dtype)


def _kernel_no_scale(len_ref, q_ref, k_ref, v_ref, o_ref,
                     m_ref, l_ref, acc_ref, **kw):
    _decode_kernel(len_ref, q_ref, k_ref, v_ref, None, None, o_ref,
                   m_ref, l_ref, acc_ref, **kw)


@functools.partial(
    jax.jit, static_argnames=("window", "block_k", "layer", "interpret"))
def flash_decode(q, ck, cv, length, k_scale=None, v_scale=None, *,
                 window: int = 0, block_k: int = 2048,
                 layer: int | None = None, interpret: bool = False):
    """Cached decode attention for ONE new token per sequence.

    q: [B, kvH, rep, D] current-position queries, grouped by kv head
    ck/cv: [B, kvH, M, D] cache buffers (bf16, or int8 with scales) — or
        the FULL [Ly, B, kvH, M, D] stack with ``layer`` set: the kernel
        then indexes the layer in its BlockSpecs, so the caller's
        per-layer slice never materializes (an XLA slice feeding a pallas
        operand is a real copy — 34MB/layer at 16k, measured ~0.6ms/step
        of pure overhead across the flagship's 12 layers)
    length: scalar int32 — the new token's absolute position (its K/V
        already written at this index); every row at the same offset
        (generate's lockstep path — the serving ring layout keeps the
        XLA path)
    k_scale/v_scale: [B, kvH, M] scales ([Ly, B, kvH, M] with ``layer``)
    -> [B, kvH, rep, D] attention output in q's dtype.

    The KV length M need not divide block_k: the tail block's
    out-of-bounds lanes load unspecified values that the position mask
    discards (length < M always).
    """
    b, kvh, rep, d = q.shape
    m_cap = ck.shape[-2]
    # one whole-cache block when the cache is small (a block larger than
    # the array is illegal; equal is); 2048 measured best at 16k on v5e
    # (1.2x the int8 streaming bound; 512 ran 2.6x)
    block_k = min(block_k, m_cap)
    n_blocks = pl.cdiv(m_cap, block_k)
    int8 = k_scale is not None

    if layer is None:
        kv_spec = pl.BlockSpec(
            (1, 1, block_k, d), lambda b_, h, j: (b_, h, j, 0))
        sc_spec = pl.BlockSpec(
            (1, 1, 1, block_k), lambda b_, h, j: (b_, h, 0, j))
        sc = lambda s: s[:, :, None, :]
    else:
        kv_spec = pl.BlockSpec(
            (1, 1, 1, block_k, d), lambda b_, h, j: (layer, b_, h, j, 0))
        sc_spec = pl.BlockSpec(
            (1, 1, 1, 1, block_k), lambda b_, h, j: (layer, b_, h, 0, j))
        sc = lambda s: s[:, :, :, None, :]

    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),        # length scalar
        pl.BlockSpec((1, 1, rep, d), lambda b_, h, j: (b_, h, 0, 0)),
        kv_spec,
        kv_spec,
    ]
    args = [jnp.asarray(length, jnp.int32)[None], q, ck, cv]
    if int8:
        # trailing [1, block_k] so the streamed block is TPU-legal
        in_specs += [sc_spec, sc_spec]
        args += [sc(k_scale), sc(v_scale)]

    kernel = functools.partial(
        _decode_kernel if int8 else _kernel_no_scale,
        scale=d ** -0.5, block_k=block_k, n_blocks=n_blocks, window=window,
    )
    return pl.pallas_call(
        kernel,
        grid=(b, kvh, n_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, rep, d), lambda b_, h, j: (b_, h, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, kvh, rep, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, 1), jnp.float32),
            pltpu.VMEM((rep, d), jnp.float32),
        ],
        interpret=interpret,
    )(*args)


__all__ = ["flash_decode"]
