"""Flagship decoder-only transformer, TPU-first.

Design choices driven by the hardware (SURVEY.md §7):
- all heavy math is batched matmuls in bf16 -> MXU; params kept in f32
- layers are stacked and iterated with `lax.scan` (one trace, fast compile,
  params carry a leading "layers" logical axis)
- attention is pluggable: fused Pallas flash kernel (ops/attention.py) on a
  single device's sequence, or ring attention (parallel/ring_attention.py)
  when the sequence is sharded over the `seq` mesh axis
- optional MoE MLP (parallel/expert.py) with experts sharded over `expert`
- every parameter carries logical axes so DP/FSDP/TP/EP placement is a
  rule-table choice (parallel/sharding.py), not a model edit
- `jax.checkpoint` on the layer body trades FLOPs for HBM when remat=True

Plain functional style: params are a pytree, `init` builds them,
`param_logical_axes` mirrors the tree with logical-axis tuples, `apply` is a
pure function ready for jit/grad.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp

from ..parallel.expert import load_balancing_loss, moe_ffn
from ..parallel.ring_attention import reference_attention


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8           # < n_heads => GQA
    d_ff: int = 2048
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    # ("llama3", factor, low_freq_factor, high_freq_factor, original_max
    # _position_embeddings) or None — Llama-3.x context-extension rope
    # (a tuple, not a dict: the config is a static jit argument)
    rope_scaling: tuple | None = None
    dtype: Any = jnp.bfloat16     # activation dtype
    param_dtype: Any = jnp.float32
    # MoE: n_experts=0 => dense SwiGLU MLP everywhere
    n_experts: int = 0
    expert_top_k: int = 2
    capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01
    # attention implementation: "flash" (pallas), "ref" (XLA), "ring" /
    # "ulysses" (sequence-parallel over the `seq` mesh axis), or "auto"
    attn_impl: str = "auto"
    # per-step kernel inside the ring SP path: "auto" (flash on TPU when the
    # shape fits the envelope, else XLA blocks), or force "flash"/"xla" —
    # "flash" off-TPU runs the Pallas kernel in interpret mode, which is how
    # the multichip dryrun covers the kernel x SP composition on a CPU mesh
    sp_kernel: str = "auto"
    # sliding-window (local) attention: each position sees its last
    # attn_window positions inclusive; 0 = full causal. Supported by the
    # flash and ref paths (block-pruned O(L*window) in the kernel)
    attn_window: int = 0
    # RMSNorm epsilon — HF Llama uses 1e-6, Mistral 1e-5; must match the
    # source model for imported checkpoints (models/hf_import.py)
    norm_eps: float = 1e-6
    # causal=False turns the stack into a bidirectional ENCODER (BERT-style:
    # every position attends everywhere). Pair with -1-masked targets for
    # masked-LM training (token_nll scores only the unmasked positions);
    # KV-cache generation requires causal=True
    causal: bool = True
    remat: bool = False
    # remat policy when remat=True: "full" rematerializes everything
    # (lowest memory, ~1 extra fwd of recompute); "dots" saves matmul
    # outputs and recomputes only elementwise ops (jax dots_saveable) —
    # most of full-remat's memory saving at a fraction of its FLOPs cost
    remat_policy: str = "full"
    # cross-entropy: "dense" materializes [B,L,V] logits; "blockwise" streams
    # the vocab in ce_block_v blocks (ops/cross_entropy.py) so nothing of
    # size [N,V] is ever live; "auto" goes blockwise at vocab >= 16384 unless
    # the mesh has a tensor axis (vocab-sharded dense wins there)
    ce_impl: str = "auto"
    ce_block_v: int = 2048

    @property
    def head_dim(self) -> int:
        return self.d_model // self.n_heads


# ------------------------------------------------------------------ building

def _dense_init(key, shape, in_axis_size, dtype):
    scale = in_axis_size ** -0.5
    return (jax.random.normal(key, shape) * scale).astype(dtype)


def init(key: jax.Array, cfg: TransformerConfig) -> dict:
    """Build the parameter pytree. Layer params are stacked [n_layers, ...]."""
    pd = cfg.param_dtype
    hd = cfg.head_dim
    keys = iter(jax.random.split(key, 16))

    def layer_stack(shape, in_size):
        k = next(keys)
        return _dense_init(k, (cfg.n_layers,) + shape, in_size, pd)

    params: dict = {
        "embed": _dense_init(next(keys), (cfg.vocab_size, cfg.d_model), cfg.d_model, pd),
        "layers": {
            "attn_norm": jnp.ones((cfg.n_layers, cfg.d_model), pd),
            "wq": layer_stack((cfg.d_model, cfg.n_heads, hd), cfg.d_model),
            "wk": layer_stack((cfg.d_model, cfg.n_kv_heads, hd), cfg.d_model),
            "wv": layer_stack((cfg.d_model, cfg.n_kv_heads, hd), cfg.d_model),
            "wo": layer_stack((cfg.n_heads, hd, cfg.d_model), cfg.n_heads * hd),
            "mlp_norm": jnp.ones((cfg.n_layers, cfg.d_model), pd),
        },
        "final_norm": jnp.ones((cfg.d_model,), pd),
        "unembed": _dense_init(next(keys), (cfg.d_model, cfg.vocab_size), cfg.d_model, pd),
    }
    if cfg.n_experts > 0:
        params["layers"].update({
            "router": layer_stack((cfg.d_model, cfg.n_experts), cfg.d_model),
            "w_in": layer_stack((cfg.n_experts, cfg.d_model, cfg.d_ff), cfg.d_model),
            "w_out": layer_stack((cfg.n_experts, cfg.d_ff, cfg.d_model), cfg.d_ff),
        })
    else:
        params["layers"].update({
            "w_gate": layer_stack((cfg.d_model, cfg.d_ff), cfg.d_model),
            "w_up": layer_stack((cfg.d_model, cfg.d_ff), cfg.d_model),
            "w_down": layer_stack((cfg.d_ff, cfg.d_model), cfg.d_ff),
        })
    return params


def param_logical_axes(cfg: TransformerConfig) -> dict:
    """Mirror of init()'s tree with logical-axis tuples for
    parallel/sharding.py rule tables."""
    layers: dict = {
        "attn_norm": ("layers", None),
        "wq": ("layers", "embed", "heads", None),
        "wk": ("layers", "embed", "kv", None),
        "wv": ("layers", "embed", "kv", None),
        "wo": ("layers", "heads", None, "embed"),
        "mlp_norm": ("layers", None),
    }
    if cfg.n_experts > 0:
        layers.update({
            "router": ("layers", "embed", None),
            "w_in": ("layers", "expert", "embed", "mlp"),
            "w_out": ("layers", "expert", "mlp", "embed"),
        })
    else:
        layers.update({
            "w_gate": ("layers", "embed", "mlp"),
            "w_up": ("layers", "embed", "mlp"),
            "w_down": ("layers", "mlp", "embed"),
        })
    return {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": (None,),
        "unembed": ("embed", "vocab"),
    }


# ------------------------------------------------------------------- pieces

def rms_norm(x, weight, eps=1e-6):
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    return (x32 * jax.lax.rsqrt(var + eps)).astype(x.dtype) * weight.astype(x.dtype)


def rope(x, positions, theta, scaling=None):
    """Rotary position embedding; x: [B, L, H, D].

    ``scaling`` — ("llama3", factor, low_freq_factor, high_freq_factor,
    original_max_position_embeddings) — applies Llama-3.x's context
    extension: frequencies whose wavelength exceeds the original context
    are slowed by ``factor``, short wavelengths are untouched, and the
    band between interpolates smoothly (the HF _compute_llama3_parameters
    rule). Every Llama 3.1+ checkpoint ships this; without it long-range
    positions are rotated off the manifold the weights were trained on."""
    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if scaling is not None:
        kind, factor, low_f, high_f, orig_max = scaling
        if kind != "llama3":
            raise ValueError(f"unsupported rope scaling kind {kind!r}")
        wavelen = 2.0 * jnp.pi / freqs
        low_wl = orig_max / low_f          # longest unscaled wavelength
        high_wl = orig_max / high_f
        smooth = jnp.clip(
            (orig_max / wavelen - low_f) / (high_f - low_f), 0.0, 1.0)
        interp = (1.0 - smooth) * freqs / factor + smooth * freqs
        freqs = jnp.where(
            wavelen < high_wl, freqs,
            jnp.where(wavelen > low_wl, freqs / factor, interp))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, L, half]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(x.dtype)


def _attention(q, k, v, cfg: TransformerConfig, mesh):
    """[B, L, H, D] in/out; dispatch on attn_impl."""
    impl = cfg.attn_impl
    if cfg.attn_window < 0:
        raise ValueError(
            f"attn_window must be >= 0 (0 = full causal), got {cfg.attn_window}"
        )
    window = cfg.attn_window or None
    if window is not None and not cfg.causal:
        raise ValueError("attn_window requires causal=True")
    if impl == "auto":
        impl = "flash" if jax.default_backend() in ("tpu", "axon") else "ref"
    if window is not None and impl in ("ring", "ulysses"):
        raise ValueError(
            f"attn_window is not supported with attn_impl={impl!r} "
            "(sequence-parallel paths are full-causal)"
        )
    if impl == "flash":
        from ..ops.attention import attention_blhd

        return attention_blhd(q, k, v, causal=cfg.causal, window=window)
    if impl == "ring":
        if mesh is None:
            raise ValueError("attn_impl='ring' requires a mesh")
        from ..parallel.ring_attention import make_ring_attention

        return make_ring_attention(
            mesh, causal=cfg.causal,
            impl=None if cfg.sp_kernel == "auto" else cfg.sp_kernel,
        )(q, k, v)
    if impl == "ulysses":
        if mesh is None:
            raise ValueError("attn_impl='ulysses' requires a mesh")
        from ..parallel.ulysses import make_ulysses_attention

        attn_fn = None  # auto: flash on TPU, reference elsewhere
        if cfg.sp_kernel == "flash":
            from ..ops.attention import attention_blhd

            attn_fn = functools.partial(attention_blhd, causal=cfg.causal)
        elif cfg.sp_kernel == "xla":
            attn_fn = functools.partial(
                reference_attention, causal=cfg.causal
            )
        elif cfg.sp_kernel != "auto":  # match the ring path's validation
            raise ValueError(
                f"sp_kernel must be 'auto', 'flash', or 'xla', got "
                f"{cfg.sp_kernel!r}"
            )
        return make_ulysses_attention(
            mesh, causal=cfg.causal, attn_fn=attn_fn
        )(q, k, v)
    return reference_attention(q, k, v, causal=cfg.causal, window=window)


def _qkv(cfg: TransformerConfig, h, positions, lp):
    """Projections + rope for a block of hidden states; k/v stay at
    n_kv_heads (GQA repeat happens at attention time)."""
    dt = cfg.dtype
    q = jnp.einsum("bld,dhk->blhk", h, lp["wq"].astype(dt))
    k = jnp.einsum("bld,dhk->blhk", h, lp["wk"].astype(dt))
    v = jnp.einsum("bld,dhk->blhk", h, lp["wv"].astype(dt))
    q = rope(q, positions, cfg.rope_theta, cfg.rope_scaling)
    k = rope(k, positions, cfg.rope_theta, cfg.rope_scaling)
    return q, k, v


def _repeat_kv(cfg: TransformerConfig, k, v):
    if cfg.n_kv_heads != cfg.n_heads:
        rep = cfg.n_heads // cfg.n_kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    return k, v


def _mlp(cfg: TransformerConfig, h, lp):
    """Post-attention MLP (dense SwiGLU or MoE) -> (out, aux_loss)."""
    dt = cfg.dtype
    aux = jnp.float32(0)
    if cfg.n_experts > 0:
        b, l, d = h.shape
        flat = h.reshape(b * l, d)
        router_logits = flat.astype(jnp.float32) @ lp["router"].astype(jnp.float32)
        out = moe_ffn(
            flat, lp["router"].astype(dt), lp["w_in"].astype(dt),
            lp["w_out"].astype(dt), k=cfg.expert_top_k,
            capacity_factor=cfg.capacity_factor, activation=jax.nn.silu,
        )
        aux = load_balancing_loss(router_logits, cfg.expert_top_k)
        return out.reshape(b, l, d), aux
    gate = jax.nn.silu(jnp.einsum("bld,df->blf", h, lp["w_gate"].astype(dt)))
    up = jnp.einsum("bld,df->blf", h, lp["w_up"].astype(dt))
    return jnp.einsum("blf,fd->bld", gate * up, lp["w_down"].astype(dt)), aux


def _layer(cfg: TransformerConfig, mesh, x, positions, lp):
    """One decoder block; lp = this layer's params (stack dim removed)."""
    dt = cfg.dtype
    h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
    q, k, v = _qkv(cfg, h, positions, lp)
    k, v = _repeat_kv(cfg, k, v)
    attn = _attention(q, k, v, cfg, mesh)
    x = x + jnp.einsum("blhk,hkd->bld", attn, lp["wo"].astype(dt))

    mlp_out, aux = _mlp(cfg, rms_norm(x, lp["mlp_norm"], cfg.norm_eps), lp)
    return x + mlp_out, aux


def apply_hidden(
    params: dict,
    tokens: jax.Array,          # [B, L] int32
    cfg: TransformerConfig,
    mesh=None,
) -> tuple[jax.Array, jax.Array]:
    """Forward pass up to (and including) the final norm -> (hidden
    [B, L, D], aux_loss scalar). The unembed projection is left to the
    caller so the loss can stream it blockwise."""
    dt = cfg.dtype
    b, l = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(l), (b, l))
    x = params["embed"].astype(dt)[tokens]

    layer_fn = functools.partial(_layer, cfg, mesh)
    if cfg.remat:
        if cfg.remat_policy == "full":
            policy = None
        elif cfg.remat_policy == "dots":
            policy = jax.checkpoint_policies.dots_saveable
        elif cfg.remat_policy == "attn":
            # save ONLY the attention output + its logsumexp (named inside
            # the flash custom_vjp forward rule, ops/attention.py — they
            # are exactly the kernel's backward residuals) so the remat
            # backward recomputes the cheap elementwise/matmul ops but
            # never re-runs the flash forward, whose cost grows
            # quadratically with L while everything else is linear
            policy = jax.checkpoint_policies.save_only_these_names(
                "attn_out", "attn_lse"
            )
        else:
            raise ValueError(
                f"remat_policy must be 'full', 'dots', or 'attn', got "
                f"{cfg.remat_policy!r}"
            )
        layer_fn = jax.checkpoint(layer_fn, policy=policy)

    def scan_body(carry, lp):
        x = carry
        x, aux = layer_fn(x, positions, lp)
        return x, aux

    x, auxes = jax.lax.scan(scan_body, x, params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.norm_eps)
    return x, jnp.sum(auxes) * cfg.aux_loss_weight


def apply(
    params: dict,
    tokens: jax.Array,          # [B, L] int32
    cfg: TransformerConfig,
    mesh=None,
) -> tuple[jax.Array, jax.Array]:
    """Forward pass -> (logits [B, L, V] f32, aux_loss scalar)."""
    x, aux = apply_hidden(params, tokens, cfg, mesh)
    logits = jnp.einsum(
        "bld,dv->blv", x, params["unembed"].astype(cfg.dtype)
    ).astype(jnp.float32)
    return logits, aux


def _use_blockwise_ce(cfg: TransformerConfig, mesh=None, rules=None) -> bool:
    if cfg.ce_impl not in ("auto", "dense", "blockwise"):
        raise ValueError(
            f"ce_impl must be 'auto', 'dense', or 'blockwise', got {cfg.ce_impl!r}"
        )
    if cfg.ce_impl == "blockwise":
        return True
    if cfg.ce_impl == "dense":
        return False
    # auto: blockwise pays at large vocab, EXCEPT when the unembed's vocab
    # dim is mesh-sharded (tensor parallelism) — the blockwise sweep's traced
    # dynamic_slice would make GSPMD gather the full unembed on every device,
    # while the dense einsum keeps logits vocab-sharded (see
    # ops/cross_entropy.py sharding note). The rules table's "vocab" row is
    # the source of truth for which axis that is; default "tensor".
    from ..parallel.sharding import mesh_shards_rule

    if mesh_shards_rule(mesh, rules, "vocab", default=("tensor",)):
        return False
    return cfg.vocab_size >= 16384


def token_nll(x, unembed, targets, cfg: TransformerConfig, mesh=None,
              rules=None, reduction: str = "mean"):
    """Masked mean next-token NLL from final hidden states, dispatching on
    cfg.ce_impl: blockwise CE streams the unembed matmul + softmax over
    vocab blocks so the [B, L, V] logits tensor never materializes (forward
    or backward); dense CE is the materializing reference path. ``auto``
    also inspects the mesh/rules: with the vocab dim mesh-sharded the dense
    path stays vocab-sharded and wins.

    x: [B, L, D] hidden (post final norm), unembed: [D, V], targets: [B, L]
    int with -1 = pad (masked out here) -> scalar mean NLL (f32).
    """
    valid = targets >= 0
    safe_targets = jnp.where(valid, targets, 0)
    if _use_blockwise_ce(cfg, mesh, rules):
        from ..ops.cross_entropy import blockwise_cross_entropy as _ce
        nll = _ce(
            x.reshape(-1, x.shape[-1]), unembed.astype(cfg.dtype),
            safe_targets.reshape(-1), cfg.ce_block_v,
        )
    else:
        from ..ops.cross_entropy import dense_cross_entropy
        nll = dense_cross_entropy(
            x.reshape(-1, x.shape[-1]), unembed.astype(cfg.dtype),
            safe_targets.reshape(-1),
        )
    nll = nll.reshape(targets.shape)
    if reduction == "sum":
        # caller divides by its own (e.g. global) valid count — the
        # pipelined head path, where per-microbatch means would up-weight
        # pad-heavy microbatches
        return (nll * valid).sum()
    return (nll * valid).sum() / jnp.maximum(valid.sum(), 1)


def loss_fn(params, tokens, targets, cfg: TransformerConfig, mesh=None,
            rules=None):
    """Next-token cross entropy (+ MoE aux); targets [B, L] with -1 = pad.

    With blockwise CE (cfg.ce_impl, default at large vocab) the [B, L, V]
    logits tensor is never materialized — the unembed matmul and softmax
    stream the vocabulary in blocks, forward and backward."""
    x, aux = apply_hidden(params, tokens, cfg, mesh)
    return token_nll(x, params["unembed"], targets, cfg, mesh, rules) + aux


def num_params(params) -> int:
    return sum(p.size for p in jax.tree.leaves(params))
