"""Keyed model registry: the serving stack's weights, named.

Until now every serving surface held exactly ONE model as an anonymous
singleton — ``SlotServer(params, cfg)``, ``serve`` loads one checkpoint,
``/stats`` renders one unlabeled model. That shape can't express the
things the reference system was built for: heterogeneous workloads side
by side on one pool (TonY's gang scheduler doesn't care what the
framework is — PAPER.md; our serving analogue is multiple *models*
behind one SlotServer/fleet). Three concrete consumers force the
registry out of the singleton:

- **Speculative decoding** is two models by construction: the draft and
  the target are just two registry entries, with ``ModelEntry.draft``
  naming the pairing so a server constructed over the registry resolves
  its draft without a side channel.
- **Multi-model serving**: ``serve --model name=spec`` (repeatable)
  registers several entries; each gets its own engine (its own slot
  pool — cache shapes are per-config), requests carry ``model=``, and
  /stats//metrics label everything per model.
- **Checkpoint hot-swap** rides the PR 7 roll/drain path: a roll
  relaunches the serve process with an updated entry ``source``;
  ``generation`` counts in-process re-registrations so tooling can see
  a swapped entry without diffing weights.

The registry is deliberately a HOST-side name table: it never touches
device memory itself. Entries hold whatever the serving layer already
accepts — raw parameter pytrees or ``prepare_decode`` bundles
(``DecodeWeights``) — so registering is free and the existing
"prepare once, drop the masters" discipline is unchanged.

No reference counterpart: TonY has no model layer (SURVEY.md §2.3);
part of the TPU-native capability extension.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

from .transformer import TransformerConfig


@dataclass
class ModelEntry:
    """One named model: decode-ready ``weights`` (raw params or a
    ``DecodeWeights`` bundle), its config, an optional ``draft`` naming
    the registry entry that speculates for it, a human-readable
    ``source`` (checkpoint path / init spec — hot-swap lineage), and a
    ``generation`` bumped on every re-registration under the same
    name."""
    name: str
    weights: Any
    cfg: TransformerConfig
    draft: str | None = None
    source: str = ""
    generation: int = 0


class ModelRegistry:
    """{name -> ModelEntry}. Registration order is preserved (the first
    entry is the default model a nameless request gets); re-registering
    a name replaces the entry and bumps its generation — the in-process
    half of a checkpoint hot-swap (the cross-process half is the PR 7
    roll/drain relaunch)."""

    def __init__(self):
        self._entries: dict[str, ModelEntry] = {}

    def register(self, name: str, weights, cfg: TransformerConfig, *,
                 draft: str | None = None, source: str = "") -> ModelEntry:
        name = str(name)
        if not name:
            raise ValueError("model name must be non-empty")
        if draft is not None and str(draft) == name:
            raise ValueError(f"model {name!r} cannot be its own draft")
        prev = self._entries.get(name)
        entry = ModelEntry(
            name=name, weights=weights, cfg=cfg,
            draft=None if draft is None else str(draft), source=source,
            generation=(prev.generation + 1 if prev is not None else 0))
        self._entries[name] = entry
        return entry

    def get(self, name: str) -> ModelEntry:
        entry = self._entries.get(str(name))
        if entry is None:
            raise KeyError(
                f"unknown model {name!r}; registered: "
                f"{sorted(self._entries) or '(none)'}")
        return entry

    def resolve_draft(self, name: str) -> ModelEntry | None:
        """The draft entry paired with ``name`` (via ``ModelEntry.
        draft``), or None when the model speculates for nobody. A
        dangling draft name is an error at resolution time, not at
        registration (entries may register in any order)."""
        entry = self.get(name)
        if entry.draft is None:
            return None
        try:
            return self.get(entry.draft)
        except KeyError:
            raise KeyError(
                f"model {name!r} names draft {entry.draft!r}, which is "
                "not registered") from None

    @property
    def default(self) -> ModelEntry:
        if not self._entries:
            raise KeyError("empty model registry")
        return next(iter(self._entries.values()))

    def names(self) -> list[str]:
        return list(self._entries)

    def __contains__(self, name: str) -> bool:
        return str(name) in self._entries

    def __len__(self) -> int:
        return len(self._entries)

    def __iter__(self):
        return iter(self._entries.values())


__all__ = ["ModelEntry", "ModelRegistry"]
