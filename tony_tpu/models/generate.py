"""Autoregressive generation with a KV cache for the flagship transformer.

The decode path the training stack doesn't need but users do. TPU-first
choices:

- **Static shapes everywhere.** The cache is allocated once at
  ``prompt_len + max_new_tokens`` (or a pinned ``max_len``) and written in
  place with ``dynamic_update_slice``; decode steps score against the full
  static cache buffer with an index mask (positions ``> current`` masked to
  -inf) instead of growing tensors — so the whole generate loop is one
  ``lax.scan`` under one jit, no per-step recompilation. Prefill is the
  exception: the cache is empty there, so it runs plain causal attention
  over the prompt via the model's own kernel (flash on TPU) rather than
  scoring against the whole buffer.
- **GQA-aware cache.** K/V are cached at ``n_kv_heads`` (the GQA-compressed
  width); heads are repeated at attention time, so cache HBM scales with
  kv-heads, not query heads.
- **One `_forward_with_cache` for prefill and decode** — same projections,
  cache writes, and unembed; they differ in the attention read (prefill:
  the model's own kernel over the prompt; decode: `_cached_attention` over
  the static buffer — see above). Dense models run fused q/k/v and gate/up
  projections (one skinny GEMV each instead of 3+2 — decode is
  weight-streaming-bound); the fusion is a concatenation of the training
  weights, so values match the `transformer._qkv`/`_mlp` path exactly.
  Weights are pre-cast to cfg.dtype once per call (identical rounding to
  the forward's per-use casts; the f32 MoE router excepted).
  `kv_dtype="int8"` and `weight_dtype="int8"` are the two opt-ins that
  genuinely change numerics vs the full forward (within int8 resolution).
  The flash-decode kernel (auto-dispatched at M>=4096 on TPU) computes
  softmax+PV in f32 like the einsum formulation, but its blockwise online
  softmax accumulates in a different ORDER — greedy tokens across the
  kernel gate agree to float tolerance, not provably bit-for-bit (a logit
  tie at f32 resolution could in principle flip; never observed in tests).

Sampling: greedy (temperature=0), temperature, and top-k. ``stop_tokens``
adds EOS semantics: a per-sequence finished mask plus a `lax.while_loop`
that exits as soon as every row has stopped, so a batch never pays decode
steps past its slowest sequence.

- **Mesh-sharded decode.** ``generate(..., mesh=..., rules=...)`` runs the
  whole loop under tensor parallelism: params are placed by the same
  logical-axis rule tables training uses (`parallel/sharding.py`), and the
  KV cache is sharded over `n_kv_heads` on the rules' "kv" axes — so a
  model bigger than one chip's HBM decodes across the mesh with the
  single-controller API unchanged. GQA models whose kv-head count doesn't
  divide the kv axes are rejected with a clear error (a split kv head has
  no layout). Use `prepare_decode` to shard + cast the weights once and
  serve many requests.

No reference counterpart: TonY has no model/inference layer (SURVEY.md
§2.3); part of the TPU-native capability layer.
"""

from __future__ import annotations

import functools
import math
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from . import transformer
from .transformer import TransformerConfig, rms_norm

NEG_INF = -1e30


class KVCache(NamedTuple):
    k: jax.Array      # [n_layers, B, n_kv_heads, max_len, head_dim]
    v: jax.Array
    length: jax.Array  # scalar int32: number of valid positions
    # int8 mode only: per-(layer, batch, kv-head, position) dequant scales
    # ([n_layers, B, n_kv_heads, max_len]); None when the cache is native
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None


def init_cache(cfg: TransformerConfig, batch: int, max_len: int,
               kv_dtype: str = "native") -> KVCache:
    """kv_dtype "native" stores cfg.dtype (exact); "int8" stores
    per-token-per-head symmetric int8 with bf16 scales — half the cache's
    HBM capacity (2x the context per GB) and, with the scale-folded
    attention reads (_cached_attention), less cache bandwidth per step
    (+16% decode throughput at max_len 1024, more at longer contexts) —
    at the cost of quantization rounding (generation is no longer
    bit-exact vs the full forward).

    Layout puts the position axis INSIDE the head axis ([..., kvH, M, D]):
    decode attention reads one head's whole history at a time, and with
    position outermost that read is strided by kvH*D — measured ~3x below
    streaming bandwidth on v5e. Head-major, each head's [M, D] block is
    contiguous."""
    shape = (cfg.n_layers, batch, cfg.n_kv_heads, max_len, cfg.head_dim)
    if kv_dtype == "int8":
        return KVCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            length=jnp.int32(0),
            k_scale=jnp.zeros(shape[:-1], jnp.bfloat16),
            v_scale=jnp.zeros(shape[:-1], jnp.bfloat16),
        )
    if kv_dtype != "native":
        raise ValueError(f"kv_dtype must be 'native' or 'int8', got {kv_dtype!r}")
    return KVCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        length=jnp.int32(0),
    )


class PrefixPool(NamedTuple):
    """Device-resident shared KV block pool for the serving prefix cache
    (models/serving.py): ``n_blocks`` chunk-sized KV blocks, each holding
    ``chunk`` consecutive positions of some cached prompt prefix.

    Layout mirrors the slot cache with the block axis where the slot axis
    sits ([layers, N, kvH, chunk, D], head-major positions inside) so a
    block copies to/from a slot ring with pure gathers/scatters — no
    transpose through a different layout on the admission hot path — and
    so a mesh shards it with the cache's own ("batch", "kv") rule: blocks
    over the batch axes, kv heads over the tensor axes. dtype matches the
    slot cache (``kv_dtype``): an int8 pool stores the QUANTIZED values
    plus their scales, so a cache hit replays byte-identical reads."""
    k: jax.Array       # [n_layers, n_blocks, n_kv_heads, chunk, head_dim]
    v: jax.Array
    k_scale: jax.Array | None = None   # int8 mode: [n_layers, n_blocks,
    v_scale: jax.Array | None = None   #             n_kv_heads, chunk]


def init_prefix_pool(cfg: TransformerConfig, n_blocks: int, chunk: int,
                     kv_dtype: str = "native") -> PrefixPool:
    """Allocate the shared prefix-cache block pool (HBM budget =
    n_blocks x the per-block KV bytes; see docs/serving.md for the
    arithmetic). Same dtype rules as init_cache."""
    shape = (cfg.n_layers, n_blocks, cfg.n_kv_heads, chunk, cfg.head_dim)
    if kv_dtype == "int8":
        return PrefixPool(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            k_scale=jnp.zeros(shape[:-1], jnp.bfloat16),
            v_scale=jnp.zeros(shape[:-1], jnp.bfloat16),
        )
    if kv_dtype != "native":
        raise ValueError(f"kv_dtype must be 'native' or 'int8', got {kv_dtype!r}")
    return PrefixPool(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
    )


def _symmetric_int8(x, axis: int):
    """Symmetric int8 quantization over `axis` -> (int8 values, f32 scales
    with `axis` kept as size 1)."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True)
    scale = jnp.maximum(amax / 127.0, 1e-8)
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)
    return q, scale


def _quantize_kv(x):
    """[B, kvH, L, D] -> (int8 values, [B, kvH, L] scales): symmetric
    per-token-per-head quantization over the head_dim vector."""
    q, scale = _symmetric_int8(x, axis=-1)
    return q, scale[..., 0].astype(jnp.bfloat16)


def _cached_attention(cfg, q, ck, cv, cache_len, l_new,
                      k_scale=None, v_scale=None, ring_offsets=None,
                      allow_kernel=True, layer_idx=None):
    """q: [B, L, H, D] for the L new positions (absolute offsets cache_len..
    cache_len+L-1); ck/cv: [B, kvH, max_len, D] full cache buffers (already
    containing the new keys). Scores run against the whole static buffer;
    invalid/future positions are masked by index. ``cache_len`` is a scalar
    (all rows at the same offset — generate) or a [B] vector (each row at
    its own offset — the serving slot pool, models/serving.py).

    GQA is a grouped einsum — query heads are folded to [kvH, rep] and
    contracted against the UN-repeated cache, so no n_heads-wide copy of
    the cache is ever materialized (that copy would undo the compressed
    cache's HBM saving on every decode step).

    int8 caches arrive with per-token-per-head scales. The dequant scales
    are FOLDED OUT of the [M, D] operands: K's scale multiplies the score
    matrix columns after the matmul, V's pre-multiplies the (tiny) prob
    matrix — so the only op left on the cache operand is the int8->bf16
    convert, which XLA fuses into the matmul's operand read. (A naive
    `cache * scale[..., None]` materializes a full dequantized buffer per
    step and erases int8's bandwidth saving.)

    ``ring_offsets`` [B] (serving slot pool): each row's buffer is a RING
    whose index m holds logical position (m - offset_b) mod M. Offsets are
    chosen at admission so every active row's next write lands at the same
    global cursor index (see models/serving.py) — the mask maps indices to
    logical positions per row; nothing else changes."""
    b, l, h, d = q.shape
    kvh = ck.shape[1 if layer_idx is None else 2]
    rep = h // kvh
    if (allow_kernel and l == 1 and jnp.ndim(cache_len) == 0
            and ring_offsets is None and cfg.attn_impl != "ref"
            and ck.shape[-2] >= 4096
            and jax.default_backend() in ("tpu", "axon")):
        # long-context single-token lockstep decode on a real chip: the
        # split-KV Pallas kernel streams the cache at ~1.2x its HBM bound
        # where this function's einsum graph measured ~4.3x (16k context,
        # v5e) — ops/decode_attention.py. Below ~4k positions the einsum
        # wins (12 kernel launches/step of fixed cost vs a small cache
        # read: measured crossover between M=2048 and 4096). With
        # layer_idx the kernel indexes the full cache stack itself
        # (slicing a pallas operand is a real copy). Mesh-sharded (GSPMD)
        # and serving-ring paths keep the XLA formulation.
        from ..ops.decode_attention import flash_decode

        out = flash_decode(
            q.reshape(b, kvh, rep, d), ck, cv, cache_len,
            k_scale, v_scale, window=cfg.attn_window or 0,
            layer=layer_idx,
        )
        return out.reshape(b, 1, h, d)
    if layer_idx is not None:           # einsum path works on the slice
        ck, cv = ck[layer_idx], cv[layer_idx]
        if k_scale is not None:
            k_scale, v_scale = k_scale[layer_idx], v_scale[layer_idx]
    q5 = q.reshape(b, l, kvh, rep, d)
    scale = cfg.head_dim ** -0.5
    s = jnp.einsum(
        "blgrd,bgmd->bgrlm", q5, ck.astype(cfg.dtype),
        preferred_element_type=jnp.float32,
    ) * scale                                           # [B, kvH, rep, L, M]
    if k_scale is not None:
        # per-position column scale: [B, kvH, M] -> [B, kvH, 1, 1, M]
        s = s * k_scale.astype(jnp.float32)[:, :, None, None, :]
    key_pos = jnp.arange(ck.shape[2])                   # [max_len]
    if ring_offsets is not None:
        # ring buffers: index m holds logical position (m - offset) mod M
        key_log = (key_pos[None, :] - ring_offsets[:, None]) % ck.shape[2]
    else:
        key_log = key_pos[None, :]
    if jnp.ndim(cache_len) == 0:
        q_pos = cache_len + jnp.arange(l_new)           # [L] absolute
        mask_bc = (None, None, None)                    # -> [1,1,1,L,M]
    else:
        q_pos = cache_len[:, None] + jnp.arange(l_new)  # [B, L] per-row
        mask_bc = (slice(None), None, None)             # -> [B,1,1,L,M]
    if ring_offsets is not None:
        mask = key_log[:, None, :] <= q_pos[..., :, None]
        if cfg.attn_window:
            mask &= key_log[:, None, :] > q_pos[..., :, None] - cfg.attn_window
        mask_bc = (slice(None), None, None)
    else:
        mask = key_log <= q_pos[..., :, None]           # causal + validity
        if cfg.attn_window:
            # sliding-window models must decode with the same band they
            # trained with, or generation attends to positions the model
            # never saw
            mask &= key_log > q_pos[..., :, None] - cfg.attn_window
    s = jnp.where(mask[mask_bc], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if v_scale is not None:
        p = p * v_scale.astype(jnp.float32)[:, :, None, None, :]
    out = jnp.einsum(
        "bgrlm,bgmd->blgrd", p.astype(cfg.dtype), cv.astype(cfg.dtype)
    )
    return out.reshape(b, l, h, d)


def _prefill_cfg(cfg: TransformerConfig) -> TransformerConfig:
    """The config used for the prefill attention dispatch: the model's own
    impl, except sequence-parallel impls (ring/ulysses need a mesh and a
    seq-sharded layout decode doesn't have) fall back to single-device
    auto dispatch."""
    if cfg.attn_impl in ("ring", "ulysses"):
        import dataclasses

        return dataclasses.replace(cfg, attn_impl="auto")
    return cfg


def moe_dropfree(cfg: TransformerConfig) -> TransformerConfig:
    """Decode routes B*1 tokens at a time; the training capacity formula
    (cf * tokens * k / E) would then drop any token that collides with
    another on the same expert. E/k guarantees capacity >= token count ->
    drop-free decode (and drop-free prefill, so cached generation matches
    the full forward whenever that forward doesn't drop). The ONE place
    this bound lives — generate and speculative_generate both call it, and
    their output-exactness contract depends on them agreeing."""
    if cfg.n_experts <= 0:
        return cfg
    import dataclasses

    return dataclasses.replace(
        cfg, capacity_factor=max(cfg.capacity_factor,
                                 cfg.n_experts / cfg.expert_top_k),
    )


def _cast_decode_params(params, cfg: TransformerConfig):
    """Pre-cast f32 master weights to the activation dtype once per
    generate call. Decode is weight-bandwidth-bound — every step reads the
    full parameter set, and the training-path convention of casting at use
    (`.astype(dt)` per op) makes each step read 2x the bytes AND write a
    copy. Numerically identical to the full forward for every weight the
    forward reads at cfg.dtype (same f32->bf16 rounding; the per-use casts
    become no-ops). The MoE ROUTER is the one exception — `_mlp`
    deliberately reads it at f32 so expert choice isn't perturbed by
    rounding — so it keeps its dtype."""
    if cfg.dtype == jnp.float32:
        return params
    router = params["layers"].get("router") if cfg.n_experts > 0 else None
    params = jax.tree.map(
        lambda a: a.astype(cfg.dtype) if a.dtype == jnp.float32 else a,
        params,
    )
    if router is not None:
        params["layers"]["router"] = router
    return params


def _quantize_weight(w):
    """[..., d_in, d_out] -> (int8, scales [..., 1, d_out]): symmetric
    per-output-channel quantization over the contraction axis. The scale
    folds OUT of the matmul — y = (x @ W_int8) * s — so the weight operand
    streamed from HBM is pure int8 (half the bytes of bf16), and only the
    tiny activation row pays the multiply."""
    return _symmetric_int8(w, axis=-2)


def _fuse_decode_weights(params, cfg: TransformerConfig,
                         weight_dtype: str = "native"):
    """Concatenate per-layer q/k/v and gate/up projection weights into one
    matrix each ([L, d, h*hd + 2*kvh*hd] and [L, d, 2*f]). Decode-step
    matmuls are skinny GEMVs whose cost is streaming the weight matrix;
    fusing 3+2 of them into 1+1 halves the kernel count per layer and
    streams bigger contiguous blocks. Built once per generate call
    (amortized over all decode steps); dense MLP only.

    weight_dtype="int8" additionally quantizes EVERY large decode matrix
    per-output-channel — decode is weight-bandwidth-bound, so halving the
    streamed bytes buys ~that much step time; numerics change within the
    int8 resolution (opt-in). Dense models quantize the fused qkv, gate/up,
    wo, w_down, and unembed; MoE models quantize qkv/wo/unembed plus EVERY
    expert's w_in/w_out with per-expert per-output-channel scales — the
    einsum-dispatch MoE streams all E experts' weights every decode step
    (static shapes; routing picks capacity slots, not which weights load),
    so expert weights dominate the stream and quantize just as profitably
    as dense ones. The scales fold out of the matmuls (parallel/expert.py
    moe_ffn) so the streamed operand stays pure int8.

    HBM note: the fused (and, in w8 mode, quantized) copies live ALONGSIDE
    the master params for the duration of the generate call — roughly the
    attention+MLP weight bytes of extra peak residency. Servers sized
    tightly should build them ONCE with `prepare_decode` and drop the
    master params; then no per-call copies are made at all."""
    L, d = cfg.n_layers, cfg.d_model
    dt = cfg.dtype
    lp = params["layers"]
    wqkv = jnp.concatenate([
        lp["wq"].reshape(L, d, -1),
        lp["wk"].reshape(L, d, -1),
        lp["wv"].reshape(L, d, -1),
    ], axis=-1)
    moe = cfg.n_experts > 0
    if not moe:
        w_gu = jnp.concatenate([lp["w_gate"], lp["w_up"]], axis=-1)
    if weight_dtype != "int8":
        return {"wqkv": wqkv} if moe else {"wqkv": wqkv, "w_gu": w_gu}
    big = [
        ("wqkv", wqkv),
        ("wo", lp["wo"].reshape(L, cfg.n_heads * cfg.head_dim, d)),
        ("unembed", params["unembed"]),
    ]
    if moe:
        big += [("w_in", lp["w_in"]), ("w_out", lp["w_out"])]
    else:
        big += [("w_gu", w_gu), ("w_down", lp["w_down"])]
    out = {}
    for name, w in big:
        q, s = _quantize_weight(w)
        out[name] = q
        out[name + "_s"] = s.astype(dt)
    return out


def _forward_with_cache(params, cfg: TransformerConfig, tokens, cache: KVCache,
                        fused: dict | None = None, prefill: bool = False,
                        shardings: "DecodeShardings | None" = None,
                        all_logits: bool = False, ring: tuple | None = None):
    """Run L new tokens (absolute positions cache.length..+L-1) through the
    stack, reading/writing the cache -> (last-position logits [B, V] f32,
    new cache) — or ([B, L, V], new cache) with ``all_logits=True`` (the
    speculative verify forward, models/speculative.py). ``cache.length``
    may be a [B] vector — every row then decodes at its OWN logical
    position (rope positions and attention masks per-row), which is the
    decode step of the continuous-batching slot pool (models/serving.py).
    Per-row mode requires ``ring=(cursor, offsets)``: each row's buffer is
    a ring where logical position p lives at index (p + offset_b) mod M,
    and the offsets are chosen at admission so every row's CURRENT write
    lands at the same scalar ``cursor`` index — the K/V write is then the
    same cheap shared-offset dynamic_update_slice as the lockstep path
    (per-row-offset writes lower to TPU scatters that cost more than the
    whole step), and only the mask pays the index→logical remap
    arithmetic. Active rows advance one position per step exactly as the
    cursor does, so a live row never wraps onto its own data. Scalar
    length (all rows in lockstep) is the generate() path; l > 1 per-row
    is unsupported (serving prefill has its own program). By default only
    the LAST position is projected through the unembed — generation never
    needs earlier logits, and a full [B, L, V] prefill projection would be
    a pure HBM bonfire at long prompts / large vocab (the same tensor the
    blockwise-CE training path exists to avoid); all_logits callers keep L
    small.

    The layer loop is UNROLLED (Python loop), not a lax.scan: a scan would
    have to thread the cache as per-layer xs/ys, which makes XLA re-read and
    re-write the ENTIRE cache buffer every decode step — ~2x the cache's
    footprint in pure overhead traffic on a path that is HBM-bound. Unrolled,
    the cache stays one carried buffer that each layer updates in place with
    a dynamic_update_slice of just the L new positions (donation keeps it
    zero-copy across decode steps); measured ~1.7x decode throughput on the
    flagship model at batch 8.

    ``prefill=True`` asserts the cache is EMPTY (generate's first call):
    attention over (cache + new) then reduces to causal attention within
    the block itself and runs through the model's own _attention (the
    flash kernel on TPU, O(block) memory; numerics identical to the
    training forward) instead of scoring q against the whole max_len
    buffer, whose f32 [.., L, max_len] scores OOM at long prompts (~18GB
    at L=8192, batch 8 on the flagship). A chunked-prefill caller feeding
    L > 1 into a NON-empty cache must pass prefill=False to get the
    general cached-attention path."""
    dt = cfg.dtype
    b, l = tokens.shape
    per_row = jnp.ndim(cache.length) == 1   # serving slot pool: [B] lengths
    if per_row:
        if ring is None or l != 1:
            raise ValueError(
                "per-row cache lengths require ring=(cursor, offsets) and "
                "single-token steps (the serving decode contract)")
        ring_cursor, ring_offsets = ring
        positions = cache.length[:, None] + jnp.arange(l)
    else:
        ring_cursor = ring_offsets = None
        positions = jnp.broadcast_to(cache.length + jnp.arange(l), (b, l))
    x = params["embed"].astype(dt)[tokens]
    if shardings is not None:
        # pin activations batch-sharded / model-dim-replicated so GSPMD
        # keeps the Megatron layout (psum after wo / w_down) instead of
        # resharding mid-layer
        x = lax.with_sharding_constraint(x, shardings.act)

    hd = cfg.head_dim
    nq, nkv = cfg.n_heads * hd, cfg.n_kv_heads * hd
    p_cfg = _prefill_cfg(cfg) if prefill else None
    w8 = fused is not None and "wqkv_s" in fused  # int8 decode weights
    ck, cv = cache.k, cache.v
    ks_buf, vs_buf = cache.k_scale, cache.v_scale
    int8_cache = ck.dtype == jnp.int8
    zero = jnp.int32(0)

    def write_kv(buf, new, layer):
        """Write this layer's new K/V (or int8-scale) block into the cache:
        buf [Ly, B, kvH, M(, D)], new [B, kvH, L(, D)] — one shared scalar
        offset for every row: cache.length on the lockstep path, the ring
        cursor on the per-row path (that is the point of the ring layout;
        see the function docstring)."""
        offset = cache.length if ring_cursor is None else ring_cursor
        idx = (jnp.int32(layer), zero, zero, offset)
        if new.ndim == 4:
            idx += (zero,)
        return lax.dynamic_update_slice(buf, new[None], idx)
    for i in range(cfg.n_layers):
        lp = jax.tree.map(lambda a: a[i], params["layers"])
        h = rms_norm(x, lp["attn_norm"], cfg.norm_eps)
        if fused is not None:
            qkv = jnp.einsum("bld,de->ble", h, fused["wqkv"][i].astype(dt))
            if w8:
                qkv = qkv * fused["wqkv_s"][i]
            q = qkv[..., :nq].reshape(b, l, cfg.n_heads, hd)
            k = qkv[..., nq:nq + nkv].reshape(b, l, cfg.n_kv_heads, hd)
            v = qkv[..., nq + nkv:].reshape(b, l, cfg.n_kv_heads, hd)
            q = transformer.rope(q, positions, cfg.rope_theta,
                                 cfg.rope_scaling)
            k = transformer.rope(k, positions, cfg.rope_theta,
                                 cfg.rope_scaling)
        else:
            q, k, v = transformer._qkv(cfg, h, positions, lp)
        k_hm = k.transpose(0, 2, 1, 3)  # [B, kvH, L, D] head-major
        v_hm = v.transpose(0, 2, 1, 3)
        if int8_cache:
            k_w, ks = _quantize_kv(k_hm)
            v_w, vs = _quantize_kv(v_hm)
            ks_buf = write_kv(ks_buf, ks, i)
            vs_buf = write_kv(vs_buf, vs, i)
        else:
            k_w, v_w = k_hm.astype(dt), v_hm.astype(dt)
        ck = write_kv(ck, k_w, i)
        cv = write_kv(cv, v_w, i)
        if prefill:
            kr, vr = transformer._repeat_kv(cfg, k, v)
            attn = transformer._attention(q, kr, vr, p_cfg, None)
        else:
            attn = _cached_attention(
                cfg, q, ck, cv, cache.length, l,
                ks_buf if int8_cache else None,
                vs_buf if int8_cache else None,
                ring_offsets=ring_offsets,
                # a pallas call inside the GSPMD-sharded decode would need
                # a shard_map wrapper; the sharded path keeps the einsum
                allow_kernel=shardings is None,
                layer_idx=i,
            )
        if w8:
            proj = jnp.einsum(
                "ble,ed->bld", attn.reshape(b, l, nq),
                fused["wo"][i].astype(dt),
            ) * fused["wo_s"][i]
        else:
            proj = jnp.einsum("blhk,hkd->bld", attn, lp["wo"].astype(dt))
        x = x + proj
        hh = rms_norm(x, lp["mlp_norm"], cfg.norm_eps)
        if fused is not None and "w_gu" in fused:
            gu = jnp.einsum("bld,de->ble", hh, fused["w_gu"][i].astype(dt))
            if w8:
                gu = gu * fused["w_gu_s"][i]
            gate, up = gu[..., :cfg.d_ff], gu[..., cfg.d_ff:]
            down = (fused["w_down"][i] if w8 else lp["w_down"]).astype(dt)
            mlp_out = jnp.einsum(
                "blf,fd->bld", jax.nn.silu(gate) * up, down
            )
            if w8:
                mlp_out = mlp_out * fused["w_down_s"][i]
        elif fused is not None and "w_in" in fused:
            # w8 routed experts: int8 expert weights streamed, per-expert
            # per-output-channel scales folded out of the matmuls
            # (moe_ffn applies them post-matmul, broadcast over capacity).
            # Same router/capacity/activation as transformer._mlp so
            # routing decisions match the native path exactly.
            from ..parallel.expert import moe_ffn

            flat = hh.reshape(b * l, cfg.d_model)
            mlp_out = moe_ffn(
                flat, lp["router"].astype(dt),
                fused["w_in"][i], fused["w_out"][i],
                k=cfg.expert_top_k, capacity_factor=cfg.capacity_factor,
                activation=jax.nn.silu,
                w_in_scale=fused["w_in_s"][i],
                w_out_scale=fused["w_out_s"][i],
            ).reshape(b, l, cfg.d_model)
        else:
            mlp_out, _ = transformer._mlp(cfg, hh, lp)
        x = x + mlp_out

    # all_logits=True projects EVERY position ([B, L, V]) — the speculative
    # verify forward needs the target's prediction after each drafted
    # token; L there is the small draft window, so the projection stays
    # tiny. Default projects only the last position (generation never
    # needs earlier logits; a full [B, L, V] prefill projection would be
    # a pure HBM bonfire at long prompts / large vocab).
    x_out = rms_norm(x if all_logits else x[:, -1], params["final_norm"],
                     cfg.norm_eps)
    eq = "bld,dv->blv" if all_logits else "bd,dv->bv"
    if w8:
        logits = (
            jnp.einsum(eq, x_out, fused["unembed"].astype(dt))
            * fused["unembed_s"][0]
        ).astype(jnp.float32)
    else:
        logits = jnp.einsum(
            eq, x_out, params["unembed"].astype(dt)
        ).astype(jnp.float32)
    if shardings is not None:
        logits = lax.with_sharding_constraint(logits, shardings.act)
        ck = lax.with_sharding_constraint(ck, shardings.cache)
        cv = lax.with_sharding_constraint(cv, shardings.cache)
        if int8_cache:
            ks_buf = lax.with_sharding_constraint(ks_buf, shardings.scale)
            vs_buf = lax.with_sharding_constraint(vs_buf, shardings.scale)
    new_cache = KVCache(k=ck, v=cv, length=cache.length + l,
                        k_scale=ks_buf, v_scale=vs_buf)
    return logits, new_cache


def sample_token(logits, key, temperature=0.0, top_k=0):
    """logits [B, V] -> token ids [B]. temperature=0 => greedy.

    ``temperature`` may be a [B] ARRAY (the serving slot pool: each row
    decodes at its own request's temperature) — rows at 0 take the greedy
    argmax, others sample; the select is traced, so one compiled program
    serves mixed greedy/sampled traffic. ``top_k`` likewise: a static int
    applies one threshold to every row (the O(V log k) lax.top_k path); a
    [B] int32 ARRAY gives each row its own k (0 = unfiltered) via a
    per-row kth-value threshold from one full-vocab sort — costlier than
    lax.top_k, so the serving loop only dispatches this variant when some
    admitted request actually overrides the server k."""
    if not isinstance(temperature, jax.Array):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        temps = None
        scaled = logits / temperature
    else:
        temps = temperature
        scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    if isinstance(top_k, jax.Array):
        v = scaled.shape[-1]
        srt = jnp.sort(scaled, axis=-1)             # ascending
        # row r keeps values >= the top_k[r]-th largest = srt[r, V - k];
        # k <= 0 (or k >= V) keeps everything
        idx = jnp.clip(v - top_k, 0, v - 1).astype(jnp.int32)
        kth = jnp.take_along_axis(srt, idx[:, None], axis=-1)
        keep = (top_k[:, None] <= 0) | (scaled >= kth)
        scaled = jnp.where(keep, scaled, NEG_INF)
    elif top_k > 0:
        # O(V log k) threshold, no sorted full-vocab copy on the hot path
        kth = jax.lax.top_k(scaled, top_k)[0][:, -1][:, None]
        scaled = jnp.where(scaled >= kth, scaled, NEG_INF)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    if temps is None:
        return sampled
    return jnp.where(temps > 0, sampled,
                     jnp.argmax(logits, axis=-1).astype(jnp.int32))


class DecodeShardings(NamedTuple):
    """Static (hashable) sharding triple threaded through the jitted decode:
    cache = KV buffers [layers, B, kvH, M, D], scale = int8 scale buffers
    [layers, B, kvH, M], act = activations/logits (batch axes only)."""
    cache: jax.sharding.NamedSharding
    scale: jax.sharding.NamedSharding
    act: jax.sharding.NamedSharding


class DecodeWeights(NamedTuple):
    """Decode-ready weights built once by `prepare_decode`: pre-cast (and
    pre-fused / pre-quantized / mesh-sharded) so repeated generate calls
    make no per-call weight copies. Pass in place of raw params.

    `weight_dtype` and `mesh` record what the weights were built FOR;
    generate() rejects calls whose arguments contradict them (a silently
    ignored mismatch would serve the wrong numerics or layout). `rules` is
    the logical-axis rule table the mesh placement used — consumers
    (generate, SlotServer) that are handed prepared weights recover the
    cache/activation shardings from it instead of guessing a table that
    might not match the weight layout."""
    params: Any
    fused: dict | None
    weight_dtype: str = "native"
    mesh: Any = None
    rules: Any = None


def _decode_shardings(mesh, rules) -> DecodeShardings:
    from ..parallel.sharding import sharding_for

    return DecodeShardings(
        cache=sharding_for(mesh, (None, "batch", "kv", None, None), rules),
        scale=sharding_for(mesh, (None, "batch", "kv", None), rules),
        act=sharding_for(mesh, ("batch",), rules),
    )


def _rule_size(mesh, rules, name: str) -> int:
    """Product of mesh-axis sizes sharding rule-table row `name`."""
    from ..parallel.sharding import mesh_shards_rule

    shape = dict(mesh.shape)
    return math.prod(shape[a] for a in mesh_shards_rule(mesh, rules, name))


def _validate_decode_mesh(cfg: TransformerConfig, mesh, rules) -> None:
    """Head counts must divide their sharding axes: a split head has no
    layout (the [M, D] cache block and the per-head softmax are atomic)."""
    t_kv = _rule_size(mesh, rules, "kv")
    if cfg.n_kv_heads % t_kv:
        raise ValueError(
            f"mesh-sharded decode: n_kv_heads={cfg.n_kv_heads} is not "
            f"divisible by the 'kv' mesh axes (size {t_kv}) — a GQA model "
            "with fewer kv heads than the tensor axis cannot shard its KV "
            "cache. Shrink the tensor axis, or set rules['kv'] = None to "
            "replicate the cache."
        )
    t_h = _rule_size(mesh, rules, "heads")
    if cfg.n_heads % t_h:
        raise ValueError(
            f"mesh-sharded decode: n_heads={cfg.n_heads} is not divisible "
            f"by the 'heads' mesh axes (size {t_h})"
        )


def prepare_decode(
    params,
    cfg: TransformerConfig,
    *,
    weight_dtype: str = "native",
    mesh=None,
    rules=None,
) -> DecodeWeights:
    """Build decode-ready weights ONCE, outside generate.

    Casts f32 masters to cfg.dtype, fuses qkv / gate-up (dense models),
    optionally quantizes (``weight_dtype="int8"``), and — when a mesh is
    given — device_puts every parameter by the logical-axis rule table
    (`transformer.param_logical_axes` x `parallel/sharding.py`), so the
    result is laid out exactly as the jitted decode wants it. Callers that
    drop their f32 masters after this hold only ONE resident copy of the
    model; per-request generate calls then make no weight copies at all
    (the in-call cast/fuse path costs roughly the attention+MLP weight
    bytes of extra peak HBM per call).

    Under a mesh whose rules shard heads/kv/mlp, the qkv and gate/up
    fusions are skipped: concatenating differently-sharded matrices would
    force GSPMD to reshuffle them every step, and TP decode is already
    per-device-bandwidth-bound on the sharded weights themselves
    (``weight_dtype="int8"`` is rejected there for the same reason — the
    w8a16 path streams the fused layout)."""
    if weight_dtype not in ("native", "int8"):
        raise ValueError(
            f"weight_dtype must be 'native' or 'int8', got {weight_dtype!r}"
        )
    sharded_tp = False
    if mesh is not None:
        if rules is None:
            from ..parallel.sharding import TP_DECODE_RULES
            rules = TP_DECODE_RULES
        _validate_decode_mesh(cfg, mesh, rules)
        sharded_tp = any(
            _rule_size(mesh, rules, r) > 1 for r in ("heads", "kv", "mlp")
        )
        if sharded_tp and weight_dtype == "int8":
            raise ValueError(
                "weight_dtype='int8' decode is single-device: the w8a16 "
                "path streams the fused qkv/gate-up layout, which conflicts "
                "with head/mlp-sharded weights"
            )
        from ..parallel.sharding import shard_params
        params = shard_params(
            mesh, params, transformer.param_logical_axes(cfg), rules
        )
    params = _cast_decode_params(params, cfg)
    fused = (None if sharded_tp
             else _fuse_decode_weights(params, cfg, weight_dtype))
    return DecodeWeights(params=params, fused=fused,
                         weight_dtype=weight_dtype, mesh=mesh, rules=rules)


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "max_new_tokens", "temperature", "top_k",
                     "kv_dtype", "max_len", "weight_dtype", "build_fused",
                     "stop_tokens", "pad_id", "shardings", "return_cache"),
    donate_argnames=("cache_in",),
)
def _generate_jit(
    params,
    fused,
    prompt,
    key,
    cache_in,
    *,
    cfg: TransformerConfig,
    max_new_tokens: int,
    temperature: float,
    top_k: int,
    kv_dtype: str,
    max_len: int,
    weight_dtype: str,
    build_fused: bool,
    stop_tokens: tuple,
    pad_id: int,
    shardings: DecodeShardings | None,
    return_cache: bool,
):
    """The whole generate loop under one jit: prefill once, then either a
    lax.scan of decode steps (no stop tokens: fixed trip count) or a
    lax.while_loop with a per-sequence finished mask (stop tokens: exits
    as soon as EVERY row has emitted a stop, so the batch pays for the
    slowest sequence, not for max_new_tokens). Returns
    (tokens [B, max_new], decode_steps scalar int32, final cache | None).

    ``cache_in`` continues from a previous call's returned cache (the
    prompt chunk is ingested through the general cached-attention path —
    the cache isn't empty, so the true-prefill fast path doesn't apply);
    it is DONATED, so the buffers update in place across turns. With
    ``return_cache`` the final emitted token is ingested too, so the
    returned cache holds prompt+ALL emitted tokens and the next turn's
    chunk is just the new tokens."""
    params = _cast_decode_params(params, cfg)   # no-op on prepared weights
    if build_fused:
        fused = _fuse_decode_weights(params, cfg, weight_dtype)
    b, _ = prompt.shape
    if cache_in is None:
        cache = init_cache(cfg, b, max_len, kv_dtype)
        logits, cache = _forward_with_cache(
            params, cfg, prompt, cache, fused, prefill=True,
            shardings=shardings)
    else:
        cache = cache_in
        logits, cache = _forward_with_cache(
            params, cfg, prompt, cache, fused, shardings=shardings)
    key, sub = jax.random.split(key)
    first = sample_token(logits, sub, temperature, top_k)

    def finalize(cache, last_tok):
        if not return_cache:
            return None
        # ingest the final emitted token so the cache holds the WHOLE
        # conversation so far (one extra forward, only on this path)
        _, cache = _forward_with_cache(
            params, cfg, last_tok[:, None], cache, fused,
            shardings=shardings)
        return cache

    if not stop_tokens:
        def step(carry, _):
            tok, cache, key = carry
            key, sub = jax.random.split(key)
            logits, cache = _forward_with_cache(
                params, cfg, tok[:, None], cache, fused, shardings=shardings
            )
            nxt = sample_token(logits, sub, temperature, top_k)
            return (nxt, cache, key), nxt

        # emit the sampled token so exactly max_new_tokens - 1 decode
        # forwards run (the prefill already produced the first token)
        (last, cache, _), rest = lax.scan(
            step, (first, cache, key), None, length=max_new_tokens - 1
        )
        toks = jnp.concatenate([first[None], rest], axis=0)
        return (jnp.moveaxis(toks, 0, 1), jnp.int32(max_new_tokens - 1),
                finalize(cache, last))

    stops = jnp.asarray(stop_tokens, jnp.int32)
    out = jnp.full((b, max_new_tokens), pad_id, jnp.int32)
    out = lax.dynamic_update_slice(out, first[:, None], (0, 0))
    finished = jnp.isin(first, stops)

    def cond(carry):
        i, _, _, _, finished, _ = carry
        return (i < max_new_tokens - 1) & ~jnp.all(finished)

    def body(carry):
        i, tok, cache, key, finished, out = carry
        key, sub = jax.random.split(key)
        logits, cache = _forward_with_cache(
            params, cfg, tok[:, None], cache, fused, shardings=shardings
        )
        nxt = sample_token(logits, sub, temperature, top_k)
        # finished rows emit pad and stay finished (pad may equal a stop id;
        # the OR below keeps them finished either way)
        nxt = jnp.where(finished, jnp.int32(pad_id), nxt)
        finished = finished | jnp.isin(nxt, stops)
        out = lax.dynamic_update_slice(out, nxt[:, None], (0, i + 1))
        return (i + 1, nxt, cache, key, finished, out)

    steps, last, cache, _, _, out = lax.while_loop(
        cond, body, (jnp.int32(0), first, cache, key, finished, out)
    )
    return out, steps, finalize(cache, last)


def generate(
    params,
    cfg: TransformerConfig,
    prompt: jax.Array,          # [B, Lp] int32, unpadded
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    key: jax.Array | None = None,
    kv_dtype: str = "native",
    max_len: int | None = None,
    weight_dtype: str = "native",
    stop_tokens: tuple = (),
    pad_id: int = 0,
    mesh=None,
    rules=None,
    return_steps: bool = False,
    cache: KVCache | None = None,
    return_cache: bool = False,
):
    """Generate max_new_tokens continuations -> [B, max_new_tokens] int32.

    Whole loop is jitted: prefill once, then single-token decode steps
    against the in-place cache (a fixed-length lax.scan, or a while_loop
    with early exit when ``stop_tokens`` is given).

    ``params`` may be a raw parameter pytree or a `DecodeWeights` from
    `prepare_decode` (servers: build once, drop the f32 masters, no
    per-call weight copies).

    ``kv_dtype="int8"`` stores the KV cache quantized (per-token-per-head
    symmetric int8, bf16 scales) — half the cache's HBM capacity and
    faster decode at long contexts; "native" (default) is bit-exact vs
    the full forward.

    ``weight_dtype="int8"`` (w8a16) quantizes every large decode matrix
    per-output-channel, halving the ~0.5GB/step weight stream that floors
    decode — the scales fold out of the matmuls so the streamed operand is
    pure int8. MoE models quantize every expert's w_in/w_out with
    per-expert scales (all E experts stream every step under einsum
    dispatch, so they dominate the stream). Numerics change within the
    int8 resolution; the master params are untouched (quantized once per
    call).

    ``max_len`` fixes the cache capacity independently of this call's
    prompt+new length (servers that reuse one compiled program across
    request lengths want one capacity; attention cost scales with it).

    ``stop_tokens`` (EOS): rows that emit any listed token stop; their
    remaining positions are ``pad_id``. The emitted stop token itself IS
    included in the output. Decode exits when all rows have stopped, so
    the step count is bounded by the slowest sequence. ``return_steps=True``
    additionally returns the number of decode forwards executed.

    ``mesh`` + ``rules`` run the whole loop tensor-parallel: weights placed
    by the training rule tables (default `TP_DECODE_RULES`), the KV cache
    sharded over kv heads on the rules' "kv" axes, activations psum'd after
    wo / w_down exactly as in Megatron-style training. n_kv_heads (and
    n_heads) must divide their sharding axes — GQA models with fewer kv
    heads than the tensor axis are rejected. qkv/gate-up fusion and w8a16
    are single-device-only and disabled/rejected under a sharded mesh.

    ``return_cache=True`` additionally returns the KV cache holding
    prompt + ALL emitted tokens; pass it back as ``cache=`` on the next
    call with only the NEW tokens as the prompt — multi-turn chat never
    re-prefills history, and greedy continuation is token-exact vs a
    one-shot generate over the concatenated conversation (tested). The
    passed cache is DONATED (updated in place — jnp.copy it first to fan
    several continuations out of one shared prefix), so ``cache=`` requires
    ``return_cache=True``: without it the conversation state would be
    consumed with no replacement returned; its capacity must
    hold the new chunk + max_new_tokens, so size the FIRST call's
    ``max_len`` for the whole conversation. After an EOS stop, finished
    rows' caches contain the pad tail — continuing them is meaningless."""
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}"
        )
    if not cfg.causal:
        raise ValueError(
            "generate requires causal=True (a bidirectional encoder has no "
            "autoregressive decode)"
        )
    if weight_dtype not in ("native", "int8"):
        raise ValueError(
            f"weight_dtype must be 'native' or 'int8', got {weight_dtype!r}"
        )
    if key is None:
        key = jax.random.PRNGKey(0)
    b, lp_len = prompt.shape
    if cache is not None:
        if not return_cache:
            raise ValueError(
                "cache= requires return_cache=True: the passed cache is "
                "donated (updated in place), so without returning it the "
                "conversation state would be irrecoverably consumed. On a "
                "final turn, pass return_cache=True and drop the result."
            )
        cap = cache.k.shape[3]
        if cache.k.shape[1] != b:
            raise ValueError(
                f"continuation batch {b} != cache batch {cache.k.shape[1]}"
            )
        used = int(cache.length)
        if used + lp_len + max_new_tokens > cap:
            raise ValueError(
                f"cache capacity {cap} cannot hold {used} cached + "
                f"{lp_len} new prompt + {max_new_tokens} generated tokens "
                "— size the first call's max_len for the whole conversation"
            )
        if max_len is not None and max_len != cap:
            raise ValueError(
                f"max_len={max_len} conflicts with the passed cache's "
                f"capacity {cap} (omit max_len when continuing)"
            )
        max_len = cap
        cache_kv = "int8" if cache.k.dtype == jnp.int8 else "native"
        if kv_dtype != "native" and kv_dtype != cache_kv:
            raise ValueError(
                f"kv_dtype={kv_dtype!r} conflicts with the passed cache "
                f"({cache_kv})"
            )
        kv_dtype = cache_kv
    elif max_len is None:
        max_len = lp_len + max_new_tokens
    elif max_len < lp_len + max_new_tokens:
        raise ValueError(
            f"max_len={max_len} < prompt ({lp_len}) + max_new_tokens "
            f"({max_new_tokens})"
        )

    shardings = None
    if mesh is not None:
        if rules is None and isinstance(params, DecodeWeights):
            # prepared weights remember the rule table their layout used;
            # defaulting to a different table here would make GSPMD
            # reshard them every call
            rules = params.rules
        if rules is None:
            from ..parallel.sharding import TP_DECODE_RULES
            rules = TP_DECODE_RULES
        _validate_decode_mesh(cfg, mesh, rules)
        t_b = _rule_size(mesh, rules, "batch")
        if b % t_b:
            raise ValueError(
                f"mesh-sharded decode: batch {b} is not divisible by the "
                f"'batch' mesh axes (size {t_b})"
            )
        shardings = _decode_shardings(mesh, rules)
        # commit the inputs so jit doesn't guess a placement: prompt batch-
        # sharded like the activations, key replicated
        prompt = jax.device_put(prompt, shardings.act)
        key = jax.device_put(
            key, jax.sharding.NamedSharding(mesh, jax.sharding.PartitionSpec())
        )

    if isinstance(params, DecodeWeights):
        prepared = params
        build_fused = False
        if weight_dtype != "native" and weight_dtype != prepared.weight_dtype:
            raise ValueError(
                f"weight_dtype={weight_dtype!r} requested but the prepared "
                f"weights were built with {prepared.weight_dtype!r} — pass "
                "weight_dtype to prepare_decode instead"
            )
        prep_mesh = prepared.mesh
        if (mesh is None) != (prep_mesh is None) or (
            mesh is not None and mesh != prep_mesh
        ):
            raise ValueError(
                "mesh mismatch: prepared weights were built "
                + ("without a mesh" if prep_mesh is None
                   else "for a different mesh")
                + (" but generate was called with one" if prep_mesh is None
                   else f" ({prep_mesh} != {mesh})")
                + " — rebuild with prepare_decode(..., mesh=...) matching "
                "the generate call"
            )
    elif mesh is not None:
        prepared = prepare_decode(
            params, cfg, weight_dtype=weight_dtype, mesh=mesh, rules=rules
        )
        build_fused = False
    else:
        prepared = DecodeWeights(params=params, fused=None)
        build_fused = True

    cfg = moe_dropfree(cfg)

    out, steps, cache_out = _generate_jit(
        prepared.params, prepared.fused, prompt, key, cache,
        cfg=cfg, max_new_tokens=max_new_tokens, temperature=temperature,
        top_k=top_k, kv_dtype=kv_dtype, max_len=max_len,
        weight_dtype=weight_dtype, build_fused=build_fused,
        stop_tokens=tuple(int(t) for t in stop_tokens), pad_id=int(pad_id),
        shardings=shardings, return_cache=return_cache,
    )
    result = (out,)
    if return_steps:
        result += (steps,)
    if return_cache:
        result += (cache_out,)
    return result if len(result) > 1 else out


__all__ = [
    "KVCache", "init_cache", "generate", "sample_token",
    "prepare_decode", "DecodeWeights", "moe_dropfree",
    "PrefixPool", "init_prefix_pool",
]
