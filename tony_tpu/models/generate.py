"""Autoregressive generation with a KV cache for the flagship transformer.

The decode path the training stack doesn't need but users do. TPU-first
choices:

- **Static shapes everywhere.** The cache is allocated once at
  ``prompt_len + max_new_tokens`` and written in place with
  ``dynamic_update_slice``; attention always scores against the full cache
  buffer with an index mask (positions ``> current`` masked to -inf) instead
  of growing tensors — so the whole generate loop is one ``lax.scan`` under
  one jit, no per-step recompilation.
- **GQA-aware cache.** K/V are cached at ``n_kv_heads`` (the GQA-compressed
  width); heads are repeated at attention time, so cache HBM scales with
  kv-heads, not query heads.
- **Prefill != decode only in length.** One `_forward_with_cache` handles
  both: prefill runs it at L=prompt_len (causal within the block), each
  decode step at L=1 — same weights path as training (`transformer._qkv`,
  `_mlp`), so there is no train/serve numerical drift.

Sampling: greedy (temperature=0), temperature, and top-k.

No reference counterpart: TonY has no model/inference layer (SURVEY.md
§2.3); part of the TPU-native capability layer.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from . import transformer
from .transformer import TransformerConfig, rms_norm

NEG_INF = -1e30


class KVCache(NamedTuple):
    k: jax.Array      # [n_layers, B, max_len, n_kv_heads, head_dim]
    v: jax.Array
    length: jax.Array  # scalar int32: number of valid positions


def init_cache(cfg: TransformerConfig, batch: int, max_len: int) -> KVCache:
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    return KVCache(
        k=jnp.zeros(shape, cfg.dtype),
        v=jnp.zeros(shape, cfg.dtype),
        length=jnp.int32(0),
    )


def _cached_attention(cfg, q, ck, cv, cache_len, l_new):
    """q: [B, L, H, D] for the L new positions (absolute offsets cache_len..
    cache_len+L-1); ck/cv: [B, max_len, kvH, D] full cache buffers (already
    containing the new keys). Scores run against the whole static buffer;
    invalid/future positions are masked by index.

    GQA is a grouped einsum — query heads are folded to [kvH, rep] and
    contracted against the UN-repeated cache, so no n_heads-wide copy of
    the cache is ever materialized (that copy would undo the compressed
    cache's HBM saving on every decode step)."""
    b, l, h, d = q.shape
    kvh = ck.shape[2]
    rep = h // kvh
    q5 = q.reshape(b, l, kvh, rep, d)
    scale = cfg.head_dim ** -0.5
    s = jnp.einsum(
        "blgrd,bmgd->bgrlm", q5, ck, preferred_element_type=jnp.float32
    ) * scale                                           # [B, kvH, rep, L, M]
    key_pos = jnp.arange(ck.shape[1])                   # [max_len]
    q_pos = cache_len + jnp.arange(l_new)               # [L] absolute
    mask = key_pos[None, :] <= q_pos[:, None]           # causal + validity
    if cfg.attn_window:
        # sliding-window models must decode with the same band they trained
        # with, or generation attends to positions the model never saw
        mask &= key_pos[None, :] > q_pos[:, None] - cfg.attn_window
    s = jnp.where(mask[None, None, None], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bgrlm,bmgd->blgrd", p.astype(cv.dtype), cv)
    return out.reshape(b, l, h, d)


def _forward_with_cache(params, cfg: TransformerConfig, tokens, cache: KVCache):
    """Run L new tokens (absolute positions cache.length..+L-1) through the
    stack, reading/writing the cache -> (last-position logits [B, V] f32,
    new cache). Only the LAST position is projected through the unembed —
    generation never needs earlier logits, and a full [B, L, V] prefill
    projection would be a pure HBM bonfire at long prompts / large vocab
    (the same tensor the blockwise-CE training path exists to avoid)."""
    dt = cfg.dtype
    b, l = tokens.shape
    positions = jnp.broadcast_to(cache.length + jnp.arange(l), (b, l))
    x = params["embed"].astype(dt)[tokens]

    def body(x, layer_in):
        lp, ck_l, cv_l = layer_in
        h = rms_norm(x, lp["attn_norm"])
        q, k, v = transformer._qkv(cfg, h, positions, lp)
        ck_l = lax.dynamic_update_slice_in_dim(ck_l, k.astype(dt), cache.length, axis=1)
        cv_l = lax.dynamic_update_slice_in_dim(cv_l, v.astype(dt), cache.length, axis=1)
        attn = _cached_attention(cfg, q, ck_l, cv_l, cache.length, l)
        x = x + jnp.einsum("blhk,hkd->bld", attn, lp["wo"].astype(dt))
        mlp_out, _ = transformer._mlp(cfg, rms_norm(x, lp["mlp_norm"]), lp)
        return x + mlp_out, (ck_l, cv_l)

    x, (new_k, new_v) = lax.scan(body, x, (params["layers"], cache.k, cache.v))
    x_last = rms_norm(x[:, -1], params["final_norm"])
    logits = jnp.einsum(
        "bd,dv->bv", x_last, params["unembed"].astype(dt)
    ).astype(jnp.float32)
    new_cache = KVCache(k=new_k, v=new_v, length=cache.length + l)
    return logits, new_cache


def sample_token(logits, key, temperature: float = 0.0, top_k: int = 0):
    """logits [B, V] -> token ids [B]. temperature=0 => greedy."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        # O(V log k) threshold, no sorted full-vocab copy on the hot path
        kth = jax.lax.top_k(logits, top_k)[0][:, -1][:, None]
        logits = jnp.where(logits >= kth, logits, NEG_INF)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@functools.partial(
    jax.jit, static_argnames=("cfg", "max_new_tokens", "temperature", "top_k")
)
def generate(
    params,
    cfg: TransformerConfig,
    prompt: jax.Array,          # [B, Lp] int32, unpadded
    max_new_tokens: int,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    key: jax.Array | None = None,
) -> jax.Array:
    """Generate max_new_tokens continuations -> [B, max_new_tokens] int32.

    Whole loop is jitted: prefill once, then a lax.scan of single-token
    decode steps against the in-place cache."""
    if max_new_tokens < 1:
        raise ValueError(
            f"max_new_tokens must be >= 1, got {max_new_tokens}"
        )
    if not cfg.causal:
        raise ValueError(
            "generate requires causal=True (a bidirectional encoder has no "
            "autoregressive decode)"
        )
    if key is None:
        key = jax.random.PRNGKey(0)
    if cfg.n_experts > 0:
        # decode routes B*1 tokens at a time; the training capacity formula
        # (cf * tokens * k / E) would then drop any token that collides with
        # another on the same expert. E/k guarantees capacity >= token count
        # -> drop-free decode (and drop-free prefill, so cached generation
        # matches the full forward whenever that forward doesn't drop).
        import dataclasses
        cfg = dataclasses.replace(
            cfg, capacity_factor=max(
                cfg.capacity_factor, cfg.n_experts / cfg.expert_top_k),
        )
    b, lp_len = prompt.shape
    cache = init_cache(cfg, b, lp_len + max_new_tokens)
    logits, cache = _forward_with_cache(params, cfg, prompt, cache)
    key, sub = jax.random.split(key)
    first = sample_token(logits, sub, temperature, top_k)

    def step(carry, _):
        tok, cache, key = carry
        key, sub = jax.random.split(key)
        logits, cache = _forward_with_cache(params, cfg, tok[:, None], cache)
        nxt = sample_token(logits, sub, temperature, top_k)
        return (nxt, cache, key), nxt

    # emit the sampled token so exactly max_new_tokens - 1 decode forwards
    # run (the prefill already produced the first token's logits)
    (_, _, _), rest = lax.scan(
        step, (first, cache, key), None, length=max_new_tokens - 1
    )
    toks = jnp.concatenate([first[None], rest], axis=0)
    return jnp.moveaxis(toks, 0, 1)                     # [B, max_new]


__all__ = ["KVCache", "init_cache", "generate", "sample_token"]
